"""Forward may-taint dataflow over one function body.

The device-sync taint rule needs to answer "can this expression hold a
device value here?" — which means tracking assignments, not just
spotting call spellings. This is a small abstract interpreter over the
statement list of one function:

  * the abstract value of a variable is its **origin set** — a set of
    labels: the distinguished DEVICE label (the value came from a
    ``jnp.*``/``jax.*`` computation) and/or parameter names (the value
    flows from that parameter, so the caller decides);
  * statements are interpreted in source order; branches are analyzed
    with copies of the state and merged by union (may-analysis); loop
    bodies get a second pass so taint fed back through the loop header
    is seen (two passes reach the fixed point for sets that only grow);
  * nested defs are skipped — they are their own functions in the call
    graph — and ``del``/strong updates remove taint (assigning a fresh
    host value to a name cleans it).

Interprocedural facts come in through two callbacks supplied by the
checker (which owns the call-graph fixed point): does this call return
a device value, and which parameters of this call's target flow into a
host sync inside it. The walker reports events — host-sync sinks and
tainted arguments crossing into sink parameters — through ``on_sink``;
the checker decides which events are findings (only hot-reachable code
is, and the DEVICE label vs parameter labels decide where to report).

Everything here is checker-agnostic plumbing; the device vocabulary
(what is a source, what is a sink) lives with the checker.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, FrozenSet, List, Optional, Set, Tuple

DEVICE = "<device>"

# Host conversions that synchronize when fed a device value. Each maps
# to the reason fragment used in findings. (len() is absent on purpose:
# array shapes are static under jax, len never blocks.)
SINK_NAME_CALLS = {
    "float": "float()",
    "bool": "bool()",
    "int": "int()",
}
SINK_ATTR_CALLS = {
    "item": ".item()",
    "tolist": ".tolist()",
    "block_until_ready": ".block_until_ready()",
}
SINK_DOTTED_CALLS = {
    "np.asarray": "np.asarray()",
    "np.array": "np.array()",
    "numpy.asarray": "numpy.asarray()",
    "numpy.array": "numpy.array()",
    "jax.device_get": "jax.device_get()",
    "jax.block_until_ready": "jax.block_until_ready()",
}

# Array metadata that lives on the host under jax: reading it never
# syncs and the result is a plain Python value.
HOST_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "itemsize", "nbytes",
    "weak_type", "sharding", "device",
})
# Methods whose result is host metadata even on a device receiver.
HOST_RESULT_METHODS = frozenset({"devices", "platform", "is_deleted"})


class SinkEvent:
    """One place a (possibly) device-origin value hits the host."""

    __slots__ = ("node", "reason", "origins", "through")

    def __init__(self, node: ast.AST, reason: str,
                 origins: FrozenSet[str],
                 through: Optional[Tuple[str, str]] = None):
        self.node = node            # where to report
        self.reason = reason        # "float()", "branching", ...
        self.origins = origins      # DEVICE and/or parameter names
        self.through = through      # (callee qualname, callee path) when
        #                             the sink is inside a callee


class FunctionTaint:
    """Interpret one function; collect SinkEvents and a summary.

    `returns_device(call)` / `sink_for_arg(call, arg)` are the
    checker's call-graph oracles: whether the call's resolved target
    returns a device value, and — for a positional index or keyword
    name — the (reason, (callee qualname, callee path)) pair when that
    argument flows into a host sync inside the target (None otherwise).
    The checker owns call resolution and positional->parameter mapping.
    """

    def __init__(
        self,
        func: ast.AST,
        *,
        is_source: Callable[[ast.Call], bool],
        returns_device: Callable[[ast.Call], bool],
        sink_for_arg: Callable[
            [ast.Call, object], Optional[Tuple[str, Tuple[str, str]]]
        ],
        is_device_attr: Optional[Callable[[ast.Attribute], bool]] = None,
        param_seed: Optional[Set[str]] = None,
    ):
        self.func = func
        self.is_source = is_source
        self.returns_device = returns_device
        self.sink_for_arg = sink_for_arg
        self.is_device_attr = is_device_attr
        self.events: List[SinkEvent] = []
        self._seen_events: Set[Tuple[int, str]] = set()
        self.returns: Set[str] = set()  # origin labels of returned values
        self._env: Dict[str, Set[str]] = {}
        args = func.args
        all_args = list(args.posonlyargs) + list(args.args) + \
            list(args.kwonlyargs)
        if args.vararg:
            all_args.append(args.vararg)
        if args.kwarg:
            all_args.append(args.kwarg)
        self.param_names = [a.arg for a in all_args]
        for p in (param_seed if param_seed is not None else self.param_names):
            self._env[p] = {p}

    # -- driving --------------------------------------------------------

    def run(self) -> "FunctionTaint":
        self._exec_block(list(self.func.body), self._env)
        return self

    def _event(self, node: ast.AST, reason: str, origins: Set[str],
               through: Optional[Tuple[str, str]] = None) -> None:
        key = (id(node), reason)
        if key in self._seen_events:
            return
        self._seen_events.add(key)
        self.events.append(SinkEvent(node, reason, frozenset(origins),
                                     through=through))

    # -- statement interpretation --------------------------------------

    def _exec_block(self, stmts, env: Dict[str, Set[str]]) -> None:
        for stmt in stmts:
            self._exec(stmt, env)

    def _exec(self, stmt: ast.AST, env: Dict[str, Set[str]]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate call-graph nodes
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            origins = self._eval(value, env) if value is not None else set()
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if isinstance(stmt, ast.AugAssign):
                origins |= self._eval(stmt.target, env)
            for tgt in targets:
                self._bind(tgt, origins, env)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.returns |= self._eval(stmt.value, env)
            return
        if isinstance(stmt, ast.Expr):
            self._eval(stmt.value, env)
            return
        if isinstance(stmt, ast.If):
            self._test(stmt.test, env)
            then_env = {k: set(v) for k, v in env.items()}
            else_env = {k: set(v) for k, v in env.items()}
            self._exec_block(stmt.body, then_env)
            self._exec_block(stmt.orelse, else_env)
            self._merge(env, then_env, else_env)
            return
        if isinstance(stmt, (ast.While,)):
            self._test(stmt.test, env)
            self._exec_block(stmt.body, env)
            self._exec_block(stmt.body, env)  # loop-carried taint
            self._test(stmt.test, env)
            self._exec_block(stmt.orelse, env)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            origins = self._eval(stmt.iter, env)
            if origins:
                self._event(stmt.iter, "iteration", origins)
            self._bind(stmt.target, set(origins), env)
            # Loop bodies run twice so loop-carried taint (a name
            # tainted late, read early next iteration) is seen; event
            # dedupe keeps reports single.
            self._exec_block(stmt.body, env)
            self._exec_block(stmt.body, env)
            self._exec_block(stmt.orelse, env)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                origins = self._eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, set(origins), env)
            self._exec_block(stmt.body, env)
            return
        if isinstance(stmt, ast.Try):
            self._exec_block(stmt.body, env)
            for handler in stmt.handlers:
                h_env = {k: set(v) for k, v in env.items()}
                self._exec_block(handler.body, h_env)
                self._merge(env, h_env, env)
            self._exec_block(stmt.orelse, env)
            self._exec_block(stmt.finalbody, env)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    env.pop(tgt.id, None)
            return
        if isinstance(stmt, (ast.Assert, ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._eval(child, env)
            return
        # Everything else (pass, break, import, global, ...): no-op.

    @staticmethod
    def _merge(env, a, b) -> None:
        for k in set(a) | set(b):
            u = a.get(k, set()) | b.get(k, set())
            if u:
                env[k] = u
            else:
                env.pop(k, None)

    def _bind(self, tgt: ast.AST, origins: Set[str],
              env: Dict[str, Set[str]]) -> None:
        if isinstance(tgt, ast.Name):
            if origins:
                env[tgt.id] = set(origins)
            else:
                env.pop(tgt.id, None)  # strong update: host value cleans
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._bind(elt, set(origins), env)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, set(origins), env)
        # Attribute/subscript stores: not tracked (field-insensitive).

    def _test(self, test: ast.expr, env: Dict[str, Set[str]]) -> None:
        origins = self._eval(test, env)
        if origins:
            self._event(test, "branching", origins)

    # -- expression evaluation -----------------------------------------

    def _eval(self, node: ast.expr, env: Dict[str, Set[str]]) -> Set[str]:
        if isinstance(node, ast.Name):
            return set(env.get(node.id, ()))
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.Attribute):
            # Host metadata (x.shape, x.dtype, ...) is a plain Python
            # value; any other attribute of a tainted value propagates
            # (x.T, x.at, ...). The is_device_attr hook lets the
            # checker name known device tables (self._wants, ...).
            if node.attr in HOST_ATTRS:
                self._eval(node.value, env)
                return set()
            out = self._eval(node.value, env)
            if not out and self.is_device_attr is not None and \
                    self.is_device_attr(node):
                out = {DEVICE}
            return out
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value, env)
            self._eval(node.slice, env)
            return base
        if isinstance(node, ast.BinOp):
            return self._eval(node.left, env) | self._eval(node.right, env)
        if isinstance(node, ast.UnaryOp):
            return self._eval(node.operand, env)
        if isinstance(node, ast.BoolOp):
            out: Set[str] = set()
            for v in node.values:
                out |= self._eval(v, env)
            return out
        if isinstance(node, ast.Compare):
            out = self._eval(node.left, env)
            for c in node.comparators:
                out |= self._eval(c, env)
            # `x is None` / `x is not y` compares identity on the host;
            # no device bool materializes.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return set()
            return out
        if isinstance(node, ast.IfExp):
            self._test(node.test, env)
            return self._eval(node.body, env) | self._eval(node.orelse, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for elt in node.elts:
                out |= self._eval(elt, env)
            return out
        if isinstance(node, ast.Dict):
            out = set()
            for k in node.keys:
                if k is not None:
                    out |= self._eval(k, env)
            for v in node.values:
                out |= self._eval(v, env)
            return out
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env)
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            # str() of a device value syncs, but f-strings over scalars
            # are ubiquitous in logging; deliberately not a sink.
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self._eval(child, env)
            return set()
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            inner = {k: set(v) for k, v in env.items()}
            for gen in node.generators:
                origins = self._eval(gen.iter, inner)
                if origins:
                    self._event(gen.iter, "iteration", origins)
                self._bind(gen.target, set(origins), inner)
            out = set()
            if isinstance(node, ast.DictComp):
                out |= self._eval(node.key, inner)
                out |= self._eval(node.value, inner)
            else:
                out |= self._eval(node.elt, inner)
            return out
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self._eval(node.value, env)
        if isinstance(node, ast.Yield):
            if node.value is not None:
                self.returns |= self._eval(node.value, env)
            return set()
        if isinstance(node, ast.Lambda):
            return set()
        return set()

    def _eval_call(self, call: ast.Call, env: Dict[str, Set[str]]) -> Set[str]:
        # Evaluate arguments first (their own calls may be sinks too).
        arg_origins: List[Set[str]] = [self._eval(a, env) for a in call.args]
        kw_origins: Dict[str, Set[str]] = {}
        for kw in call.keywords:
            o = self._eval(kw.value, env)
            if kw.arg:
                kw_origins[kw.arg] = o

        reason = self._direct_sink(call)
        if reason is not None:
            hit: Set[str] = set()
            for o in arg_origins:
                hit |= o
            if not hit and isinstance(call.func, ast.Attribute):
                hit = self._eval(call.func.value, env)
            if hit:
                self._event(call, reason, hit)
            return set()  # result is a host value

        # Tainted arguments crossing into parameters that sink inside
        # the (resolved) callee.
        for i, o in enumerate(arg_origins):
            if not o:
                continue
            hit = self.sink_for_arg(call, i)
            if hit is not None:
                self._event(call, hit[0], o, through=hit[1])
        for name, o in kw_origins.items():
            if not o:
                continue
            hit = self.sink_for_arg(call, name)
            if hit is not None:
                self._event(call, hit[0], o, through=hit[1])

        out: Set[str] = set()
        if self.is_source(call):
            out.add(DEVICE)
        if self.returns_device(call):
            out.add(DEVICE)
        if isinstance(call.func, ast.Attribute):
            # A method result on a tainted receiver stays tainted
            # (x.sum(), x.astype(...), x.reshape(...)) unless the
            # method lands on the host (.item(), .devices(), ...).
            recv = self._eval(call.func.value, env)
            if recv and call.func.attr not in SINK_ATTR_CALLS and \
                    call.func.attr not in HOST_RESULT_METHODS:
                out |= recv
        return out

    @staticmethod
    def _direct_sink(call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name) and func.id in SINK_NAME_CALLS:
            return SINK_NAME_CALLS[func.id]
        if isinstance(func, ast.Attribute):
            if func.attr in SINK_ATTR_CALLS:
                return SINK_ATTR_CALLS[func.attr]
            try:
                txt = ast.unparse(func)
            except Exception:  # pragma: no cover
                return None
            if txt in SINK_DOTTED_CALLS:
                return SINK_DOTTED_CALLS[txt]
        return None
