"""Whole-program substrate for doormanlint: symbol table, import graph,
and an approximate call graph over the scanned tree.

doormanlint v1 was per-file: every checker saw one ast at a time, so a
host sync reached through a helper call, a lock-order cycle spanning two
files, or a module the hand-kept CHAOS_REACHABLE list forgot were all
invisible. This module gives the checkers the three whole-program
structures those rules need, still stdlib-only and still without ever
importing the code under analysis:

  * **symbol table** — every function/method in the tree, keyed by
    (file, qualname), with per-file import-alias maps;
  * **import graph** — repo-internal module dependencies, including the
    Python semantics that importing ``a.b.c`` executes ``a/__init__.py``
    and ``a/b/__init__.py``; ``reachable_files`` is the derivation that
    replaces hand-kept module registries (CHAOS_ROOTS below);
  * **approximate call graph** — call sites resolved best-effort:
    bare names bind to function-local defs, then module-level defs,
    then imported symbols; ``self.m()`` binds through the enclosing
    class (and its same-tree bases); ``alias.f()`` binds through the
    import-alias map; any other ``obj.m()`` falls back to the
    unique-method heuristic (resolve only when at most
    _MAX_METHOD_CANDIDATES classes in the whole tree define ``m`` and
    ``m`` is not a container/stdlib-ish name from _GENERIC_METHODS).

The call graph is deliberately approximate in the sound-enough-to-lint
sense: unresolved calls resolve to nothing (findings can be missed
through them, never invented), and the unique-method fallback is capped
so dict-shaped method names don't weld the graph into one blob.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.lint.core import FileContext, qualname

# Roots of the seeded-determinism derivation: what the chaos runner, the
# serving stack it drives, and the sim kernel (the other seeded-replay
# surface) can execute. Everything transitively imported from these is
# chaos-reachable; nothing else is. doc/lint.md "Registry derivation".
CHAOS_ROOTS = (
    "doorman_tpu/chaos/",
    "doorman_tpu/frontend/",
    "doorman_tpu/server/",
    "doorman_tpu/sim/",
    # The workload harness is the other log_sha256-pinned replay
    # surface: the vector population engine (workload.population) and
    # its generators draw from the same seeded-determinism contract
    # the chaos runner enforces.
    "doorman_tpu/workload/",
    # The fleet runtime: the chaos runner and workload harness drive
    # FleetController (reconcile beat, routing epochs, autoscaler)
    # inside the same log_sha256-pinned replays.
    "doorman_tpu/fleet/",
)

# Attribute calls resolved through the unique-method fallback only when
# the bare name is not one of these: container/protocol names that a
# dozen unrelated classes (and every dict/list/set) share would weld
# the call graph into one component.
_GENERIC_METHODS = frozenset({
    "get", "put", "pop", "add", "append", "extend", "update", "items",
    "keys", "values", "copy", "clear", "remove", "discard", "insert",
    "close", "open", "read", "write", "flush", "start", "stop", "run",
    "join", "send", "recv", "acquire", "release", "wait", "notify",
    "set", "reset", "result", "submit", "cancel", "done", "record",
    "lap", "span", "instant", "observe", "info", "debug", "warning",
    "error", "exception", "encode", "decode", "format", "strip",
    "split", "sort", "index", "count", "next", "name", "status",
    "snapshot", "to_json", "from_json",
})
_MAX_METHOD_CANDIDATES = 3


class FunctionInfo:
    """One def in the tree: identity, location, and its call sites."""

    __slots__ = ("ctx", "node", "qualname", "key", "cls", "calls")

    def __init__(self, ctx: FileContext, node: ast.AST, qn: str,
                 cls: Optional[str]):
        self.ctx = ctx
        self.node = node
        self.qualname = qn
        self.key = (ctx.relpath, qn)
        self.cls = cls  # immediately-enclosing class name, if a method
        # Calls lexically inside this def but NOT inside a nested def
        # (those belong to the nested FunctionInfo): list of
        # (ast.Call, resolved targets tuple).
        self.calls: List[Tuple[ast.Call, Tuple["FunctionInfo", ...]]] = []

    @property
    def name(self) -> str:
        return self.node.name

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<fn {self.key[0]}::{self.key[1]}>"


def _dotted(relpath: str) -> str:
    """Module dotted name of a repo-relative path."""
    mod = relpath[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _ancestor_inits(relpath: str) -> List[str]:
    """Package __init__.py files that importing this module executes."""
    out = []
    parts = relpath.split("/")
    for i in range(1, len(parts)):
        out.append("/".join(parts[:i]) + "/__init__.py")
    return out


class RepoGraph:
    """Symbol table + import graph + approximate call graph (module
    docstring). Built once per lint run from the already-parsed
    FileContexts; all lookups afterwards are dict hits."""

    def __init__(self, files: Sequence[FileContext]):
        self.files = list(files)
        self.by_path: Dict[str, FileContext] = {f.relpath: f for f in files}
        self.module_of: Dict[str, str] = {}   # dotted -> relpath
        for f in files:
            self.module_of[_dotted(f.relpath)] = f.relpath

        # relpath -> set of repo-internal relpaths it imports.
        self.imports: Dict[str, Set[str]] = {}
        # relpath -> local name -> ("module", relpath) |
        #                          ("symbol", relpath, symbol)
        self.aliases: Dict[str, Dict[str, tuple]] = {}
        for f in files:
            self._scan_imports(f)

        # Symbol table.
        self.functions: Dict[Tuple[str, str], FunctionInfo] = {}
        # (relpath, class, method) -> FunctionInfo
        self._methods: Dict[Tuple[str, str, str], FunctionInfo] = {}
        # (relpath, name) -> module-level FunctionInfo
        self._module_fns: Dict[Tuple[str, str], FunctionInfo] = {}
        # bare method name -> [FunctionInfo] (unique-method fallback)
        self._methods_by_name: Dict[str, List[FunctionInfo]] = defaultdict(list)
        # class name -> [(relpath, ClassDef)]
        self.classes: Dict[str, List[Tuple[str, ast.ClassDef]]] = defaultdict(list)
        for f in files:
            self._scan_defs(f)
        for f in files:
            self._scan_calls(f)

        # Reverse adjacency: callee key -> [(caller, call node)].
        self.callers: Dict[Tuple[str, str], List[Tuple[FunctionInfo, ast.Call]]]
        self.callers = defaultdict(list)
        for fn in self.functions.values():
            for call, targets in fn.calls:
                for t in targets:
                    self.callers[t.key].append((fn, call))

    # -- import graph ---------------------------------------------------

    def _scan_imports(self, ctx: FileContext) -> None:
        deps: Set[str] = set()
        alias: Dict[str, tuple] = {}
        # Base package for level-1 relative imports: the module's own
        # package — which is the module itself for an __init__.py.
        pkg = _dotted(ctx.relpath)
        if not ctx.relpath.endswith("__init__.py"):
            pkg = pkg.rpartition(".")[0]
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    rel = self._module_rel(a.name)
                    if rel:
                        deps.add(rel)
                        alias[a.asname or a.name.split(".")[0]] = (
                            ("module", rel) if a.asname
                            else ("module", self._module_rel(a.name.split(".")[0]) or rel)
                        )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    up = pkg.rsplit(".", node.level - 1)[0] if node.level > 1 else pkg
                    base = f"{up}.{base}" if base else up
                base_rel = self._module_rel(base)
                for a in node.names:
                    sub_rel = self._module_rel(f"{base}.{a.name}")
                    local = a.asname or a.name
                    if sub_rel:  # `from pkg import submodule`
                        deps.add(sub_rel)
                        alias[local] = ("module", sub_rel)
                    elif base_rel:  # `from module import symbol`
                        deps.add(base_rel)
                        alias[local] = ("symbol", base_rel, a.name)
        # Importing a.b.c executes a/__init__.py and a/b/__init__.py.
        for dep in list(deps):
            for init in _ancestor_inits(dep):
                if init in self.by_path:
                    deps.add(init)
        deps.discard(ctx.relpath)
        self.imports[ctx.relpath] = deps
        self.aliases[ctx.relpath] = alias

    def _module_rel(self, dotted: str) -> Optional[str]:
        return self.module_of.get(dotted)

    def reachable_files(self, root_prefixes: Iterable[str]) -> Set[str]:
        """Transitive import closure from every file under the given
        repo-relative prefixes (the roots are included)."""
        prefixes = tuple(root_prefixes)
        seen: Set[str] = set()
        stack = [p for p in self.by_path if p.startswith(prefixes)]
        while stack:
            rel = stack.pop()
            if rel in seen:
                continue
            seen.add(rel)
            stack.extend(self.imports.get(rel, ()))
        return seen

    def chaos_reachable(self) -> Set[str]:
        """The derived replacement for the old hand-kept CHAOS_REACHABLE
        prefix list (see CHAOS_ROOTS)."""
        return self.reachable_files(CHAOS_ROOTS)

    # -- symbol table ---------------------------------------------------

    def _scan_defs(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                self.classes[node.name].append((ctx.relpath, node))
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qn = qualname(ctx, node)
            parent = ctx.parents.get(node)
            cls = parent.name if isinstance(parent, ast.ClassDef) else None
            info = FunctionInfo(ctx, node, qn, cls)
            self.functions[info.key] = info
            if cls is not None:
                self._methods[(ctx.relpath, cls, node.name)] = info
                self._methods_by_name[node.name].append(info)
            elif isinstance(parent, ast.Module):
                self._module_fns[(ctx.relpath, node.name)] = info

    def function_at(self, relpath: str, qn: str) -> Optional[FunctionInfo]:
        return self.functions.get((relpath, qn))

    def method(self, relpath: str, cls: str, name: str
               ) -> Optional[FunctionInfo]:
        return self._methods.get((relpath, cls, name))

    def has_qualname(self, qn: str) -> bool:
        """Does any file define this Class.method / function?"""
        return any(key[1] == qn for key in self.functions)

    def enclosing_function(self, ctx: FileContext, node: ast.AST
                           ) -> Optional[FunctionInfo]:
        """The FunctionInfo whose body (innermost) contains node."""
        cur = ctx.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self.functions.get((ctx.relpath, qualname(ctx, cur)))
            cur = ctx.parents.get(cur)
        return None

    # -- call graph -----------------------------------------------------

    def _scan_calls(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            owner = self.enclosing_function(ctx, node)
            if owner is None:
                continue  # module-level call: import graph covers it
            targets = self.resolve_call(ctx, node, owner)
            owner.calls.append((node, targets))

    def resolve_call(self, ctx: FileContext, call: ast.Call,
                     owner: FunctionInfo) -> Tuple[FunctionInfo, ...]:
        func = call.func
        alias = self.aliases.get(ctx.relpath, {})
        if isinstance(func, ast.Name):
            # function-local nested def, then module-level, then import.
            for n in ast.walk(owner.node):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n is not owner.node and n.name == func.id:
                    info = self.functions.get((ctx.relpath, qualname(ctx, n)))
                    if info:
                        return (info,)
            info = self._module_fns.get((ctx.relpath, func.id))
            if info:
                return (info,)
            bound = alias.get(func.id)
            if bound and bound[0] == "symbol":
                info = self._module_fns.get((bound[1], bound[2]))
                if info:
                    return (info,)
            return ()
        if not isinstance(func, ast.Attribute):
            return ()
        attr = func.attr
        recv = func.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and owner.cls is not None:
                info = self._method_in_class(ctx.relpath, owner.cls, attr)
                if info:
                    return (info,)
                return self._fallback(attr)
            bound = alias.get(recv.id)
            if bound and bound[0] == "module":
                info = self._module_fns.get((bound[1], attr))
                return (info,) if info else ()
            if recv.id in self.classes:  # ClassName.method(...)
                for rel, _ in self.classes[recv.id]:
                    info = self._methods.get((rel, recv.id, attr))
                    if info:
                        return (info,)
                return ()
            return self._fallback(attr)
        if isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name):
            # a.b.attr(...): `a` may alias a package (import a.b).
            bound = alias.get(recv.value.id)
            if bound and bound[0] == "module":
                sub = self._module_rel(f"{_dotted(bound[1])}.{recv.attr}")
                if sub:
                    info = self._module_fns.get((sub, attr))
                    return (info,) if info else ()
        return self._fallback(attr)

    def _method_in_class(self, relpath: str, cls: str, name: str
                         ) -> Optional[FunctionInfo]:
        """Method lookup through the class and its same-tree bases."""
        seen: Set[str] = set()
        stack = [(relpath, cls)]
        while stack:
            rel, cname = stack.pop()
            if cname in seen:
                continue
            seen.add(cname)
            info = self._methods.get((rel, cname, name))
            if info:
                return info
            for crel, cnode in self.classes.get(cname, ()):
                if crel != rel:
                    continue
                for base in cnode.bases:
                    if isinstance(base, ast.Name):
                        for brel, _ in self.classes.get(base.id, ()):
                            stack.append((brel, base.id))
        return None

    def _fallback(self, attr: str) -> Tuple[FunctionInfo, ...]:
        if attr in _GENERIC_METHODS or attr.startswith("__"):
            return ()
        cands = self._methods_by_name.get(attr, ())
        if 0 < len(cands) <= _MAX_METHOD_CANDIDATES:
            return tuple(cands)
        return ()

    # -- reachability over calls ---------------------------------------

    def transitive_callees(self, roots: Iterable[FunctionInfo]
                           ) -> Set[Tuple[str, str]]:
        seen: Set[Tuple[str, str]] = set()
        stack = [r.key for r in roots]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            fn = self.functions.get(key)
            if fn is None:
                continue
            for _, targets in fn.calls:
                stack.extend(t.key for t in targets)
        return seen
