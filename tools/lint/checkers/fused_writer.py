"""fused-writer-discipline: the PR-7 fused-staging freshness contract.

`engine.FusedStaging` serves a tick's upload pack from a window-time
cache; a cache entry is valid only while no store write touched its row
after staging. Tracked writers (the admission coalescer's grouped pass)
re-stage what they write; EVERY other writer of lease-store rows must
drop the touched rows from the cache via `_fused_invalidate` — a writer
that does neither ships a pre-write pack whose dirty flag the next
drain consumes, and the store of record silently diverges from the
device table (the exact bug class doc/bench.md's parity rules pin).

Machine check: in the contract modules (server/server.py and
admission/coalesce.py), any function that calls a store-writing method
(`assign`, `release`, `decide`, `decide_fast`, `refresh_grant`,
`bulk_assign`, `bulk_refresh`, `regrant`, `restore`, `clean`,
`clean_all` — or `_decide`, which wraps them) must either

  * call `_fused_invalidate` (or `_fused_stage`) somewhere in its own
    body, or
  * appear in the `FUSED_TRACKED_WRITERS` registry next to
    `_fused_invalidate` in server/server.py — the audited list of
    writers whose staging obligations are owned elsewhere (the
    coalescer re-stages; callers invalidate; or staging is provably
    detached on that path).

Adding a store write to a new RPC path without deciding its staging
story now fails CI instead of shipping a one-in-a-thousand stale grant.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.lint.core import (
    Checker,
    FileContext,
    Finding,
    RepoContext,
    attr_tail,
    enclosing_functions,
    qualname,
)

SCOPE_FILES = (
    "doorman_tpu/server/server.py",
    "doorman_tpu/admission/coalesce.py",
)

# Store-row mutators across LeaseStore / NativeLeaseStore / Resource,
# plus the server's _decide wrapper (calling it IS writing).
WRITER_METHODS = {
    "assign", "regrant", "release", "restore", "bulk_assign",
    "bulk_refresh", "decide", "decide_fast", "refresh_grant",
    "clean", "clean_all", "_decide",
}
_FUSED_HOOKS = {"_fused_invalidate", "_fused_stage"}


class FusedWriterDiscipline(Checker):
    name = "fused-writer-discipline"
    description = (
        "store-row writers in server/coalesce must be registered in "
        "FUSED_TRACKED_WRITERS or call _fused_invalidate"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        if ctx.relpath not in SCOPE_FILES:
            return
        # function node -> first writer call seen (for the report site)
        writers = {}
        handles = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = attr_tail(node)
            funcs = enclosing_functions(ctx, node)
            if not funcs:
                continue
            # Obligations attach to the outermost def: a nested helper's
            # writes are the enclosing method's staging problem.
            owner = funcs[-1]
            if tail in _FUSED_HOOKS:
                handles.add(owner)
            elif tail in WRITER_METHODS and isinstance(node.func, ast.Attribute):
                writers.setdefault(owner, node)
        for func, call in writers.items():
            if func in handles:
                continue
            qn = qualname(ctx, func)
            if qn in repo.tracked_writers:
                continue
            yield self.finding(
                ctx, call,
                f"{qn} writes store rows (.{attr_tail(call)}) but neither "
                "calls _fused_invalidate/_fused_stage nor appears in "
                "FUSED_TRACKED_WRITERS (server/server.py): a staged pack "
                "of the touched row would ship pre-write values "
                "(engine.FusedStaging freshness contract)",
            )
