"""lock-discipline: `# guarded-by:` state is touched under its lock.

The coalescer stages from its executor while the tick executor takes;
the debug server answers from its own threads; the PipelinedTicker and
flight recorder straddle the event loop and the tick thread. The repo's
convention for all of that shared state is a declaration at the
assignment site:

    self._cache: Dict[int, tuple] = {}  # guarded-by: self._lock

This checker enforces the declaration: every later load or store of a
guarded attribute (or guarded module global) must sit lexically inside
`with <that lock>:`. Two escape hatches, both explicit:

  * `# holds-lock: self._lock` on a def line — the caller owns the
    lock; the body is treated as locked (the classic private-helper
    pattern);
  * `# doorman: allow[lock-discipline]` with a reason for the genuinely
    benign cases (reading a monotonically-published float, CPython
    atomic swaps).

Nested functions deliberately do NOT inherit the lexically-held lock:
a closure handed to an executor runs later, on another thread — which
is also the second half of this rule: any callable submitted to an
executor (`run_in_executor`, `.submit`, `call_soon_threadsafe`) that
mutates `self.*` state without holding SOME lock is flagged, guarded
or not. Cross-thread mutation with no lock at all is how the
coalescer/ticker races of tomorrow get written.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from tools.lint.core import (
    Checker,
    FileContext,
    Finding,
    RepoContext,
    WithLockMap,
    enclosing_class,
    enclosing_functions,
)

_EXECUTOR_CALLS = {"run_in_executor", "submit", "call_soon_threadsafe"}


class LockDiscipline(Checker):
    name = "lock-discipline"
    description = (
        "# guarded-by: attributes accessed outside their lock, and "
        "executor-submitted callables mutating shared state lock-free"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        guarded = self._collect_guarded(ctx)
        if guarded:
            yield from self._check_guarded(ctx, guarded)
        yield from self._check_executor_callables(ctx, guarded)

    # -- declaration scan ---------------------------------------------

    def _collect_guarded(self, ctx: FileContext
                         ) -> Dict[Tuple[Optional[str], str], Tuple[str, ast.AST]]:
        """(class name | None for module level, attr) -> (lock text,
        declaring function node | None)."""
        out: Dict[Tuple[Optional[str], str], Tuple[str, ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            lock = ctx.guarded_marker(node.lineno)
            if lock is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            cls = enclosing_class(ctx, node)
            funcs = enclosing_functions(ctx, node)
            decl_fn = funcs[0] if funcs else None
            for tgt in targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and cls is not None
                ):
                    out[(cls.name, tgt.attr)] = (lock, decl_fn)
                elif isinstance(tgt, ast.Name) and cls is None and decl_fn is None:
                    out[(None, tgt.id)] = (lock, None)
        return out

    # -- guarded access enforcement -----------------------------------

    def _check_guarded(self, ctx, guarded) -> Iterator[Finding]:
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if enclosing_functions(ctx, func):
                continue  # nested defs are visited through their parent's map
            cls = enclosing_class(ctx, func)
            lockmap = WithLockMap.build(func)
            held_extra = ctx.holds_marker(func)
            for node in ast.walk(func):
                key = None
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and cls is not None
                ):
                    key = (cls.name, node.attr)
                elif isinstance(node, ast.Name):
                    key = (None, node.id)
                if key is None or key not in guarded:
                    continue
                lock, decl_fn = guarded[key]
                if decl_fn is func or (decl_fn is None and func.name == "__init__"):
                    continue  # construction site
                inner = enclosing_functions(ctx, node)
                inner_fn = inner[0] if inner else func
                if inner_fn is not func and ctx.holds_marker(inner_fn) == lock:
                    continue
                if inner_fn is func and held_extra == lock:
                    continue
                if lockmap.holds(node, lock):
                    continue
                attr = key[1]
                yield self.finding(
                    ctx, node,
                    f"{attr} is declared `# guarded-by: {lock}` but is "
                    f"accessed outside `with {lock}` (annotate the def "
                    f"with `# holds-lock: {lock}` if the caller holds it)",
                )

    # -- executor-submitted callables ---------------------------------

    def _check_executor_callables(self, ctx, guarded) -> Iterator[Finding]:
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local_defs = {
                n.name: n
                for n in ast.walk(func)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not func
            }
            for node in ast.walk(func):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EXECUTOR_CALLS
                ):
                    continue
                for arg in node.args:
                    target: Optional[ast.AST] = None
                    if isinstance(arg, ast.Lambda):
                        target = arg
                    elif isinstance(arg, ast.Name) and arg.id in local_defs:
                        target = local_defs[arg.id]
                    if target is None:
                        continue
                    yield from self._check_submitted(ctx, target)

    def _check_submitted(self, ctx, target) -> Iterator[Finding]:
        """A callable that will run on another thread: flag bare
        mutations of self.* state done with no lock held at all."""
        if isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                ctx.holds_marker(target):
            return
        lockmap = WithLockMap.build(target)
        stores: List[ast.Attribute] = []
        for node in ast.walk(target):
            tgts: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                tgts = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                tgts = [node.target]
            for tgt in tgts:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                    and not lockmap.held_at.get(node)
                ):
                    stores.append(tgt)
        for tgt in stores:
            name = getattr(target, "name", "<lambda>")
            yield self.finding(
                ctx, tgt,
                f"executor-submitted callable {name!r} mutates "
                f"self.{tgt.attr} without holding any lock: it runs on "
                "another thread, racing the event loop (take a lock or "
                "annotate the def with # holds-lock:)",
            )
