"""seeded-determinism: chaos-reachable modules take time and randomness
only through injectable seams.

Chaos replays are byte-stable because every time read in the stack goes
through an injectable clock (chaos.clock.ChaosClock) and every random
draw through a seeded rng handed in by the plan. A direct
`time.time()` / `time.monotonic()` / `random.random()` /
`datetime.now()` in a chaos-reachable module silently escapes the
virtual clock: the run still passes locally and the replay diverges
under load, which is the worst kind of flake.

What stays legal, by construction rather than by suppression:

  * the seam itself — `clock: Callable[[], float] = time.time` as a
    default argument is a reference, not a call, and never matches;
  * seeded construction — `random.Random(seed)` with arguments;
  * the injectable-fallback idiom — an argless `random.Random()` inside
    a conditional expression or `or`-chain choosing against an injected
    rng (`rng if rng is not None else random.Random()`).

Genuinely wall-clock behavior (RPC deadlines against real sockets,
election retry budgets) carries `# doorman: allow[seeded-determinism]`
with its reason — the point is that every escape from virtual time is
explicit and reviewed, not that none exist.

Scope is DERIVED, not declared: a module is chaos-reachable when it is
in the transitive import closure of the chaos runner, the serving
stack, or the sim kernel (graph.CHAOS_ROOTS). The old hand-kept
CHAOS_REACHABLE prefix list rotted exactly the way hand-kept lists do
— `federation/` had to be added by review in PR 10, and a miss there
would have silently exempted a whole subsystem from this contract. Now
a new subsystem is covered the moment anything reachable imports it,
and a module nothing can reach (loadtest drivers, cmd entry points)
is exempt by construction instead of by omission.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.lint.core import Checker, FileContext, Finding, RepoContext, call_name

_TIME_CALLS = {"time.time", "time.monotonic"}
_DATETIME_CALLS = {
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "date.today", "datetime.date.today",
}
# Module-level functions of `random` that draw from the global
# (process-seeded) state.
_RANDOM_FUNCS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "expovariate",
    "betavariate", "triangular", "getrandbits", "randbytes", "vonmisesvariate",
}
_OTHER_CALLS = {"uuid.uuid4", "os.urandom", "secrets.token_bytes",
                "secrets.token_hex"}


class SeededDeterminism(Checker):
    name = "seeded-determinism"
    description = (
        "time.time()/random.*/datetime.now() in chaos-reachable modules "
        "must go through the injectable clock/rng seams"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        reachable = repo.cache.get(self.name)
        if reachable is None:
            reachable = repo.graph.chaos_reachable()
            repo.cache[self.name] = reachable
        if ctx.relpath not in reachable:
            return
        # The virtual clock itself documents/aliases time.time.
        if ctx.relpath.endswith("chaos/clock.py"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in _TIME_CALLS:
                yield self.finding(
                    ctx, node,
                    f"{name}() escapes the injectable clock seam; take a "
                    "`clock: Callable[[], float]` parameter (default "
                    f"{name}) and call that instead",
                )
            elif name in _DATETIME_CALLS:
                yield self.finding(
                    ctx, node,
                    f"{name}() is wall-clock; route through the injectable "
                    "clock seam",
                )
            elif name in _OTHER_CALLS:
                yield self.finding(
                    ctx, node,
                    f"{name}() is nondeterministic; chaos replays cannot "
                    "pin it — draw from an injected seeded rng",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "random"
                and node.func.attr in _RANDOM_FUNCS
            ):
                yield self.finding(
                    ctx, node,
                    f"random.{node.func.attr}() draws from the global rng; "
                    "use an injected seeded random.Random",
                )
            elif (
                name in ("random.Random", "Random")
                and not node.args
                and not node.keywords
                and not self._is_seam_fallback(ctx, node)
            ):
                yield self.finding(
                    ctx, node,
                    "random.Random() without a seed is nondeterministic; "
                    "seed it, or make it the fallback of an injectable "
                    "rng parameter (`rng if rng is not None else "
                    "random.Random()`)",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and ast.unparse(node.func).startswith(("np.random.", "numpy.random."))
            ):
                yield self.finding(
                    ctx, node,
                    f"{name}() uses numpy's global rng; use an injected "
                    "np.random.Generator (or a seeded Random)",
                )

    @staticmethod
    def _is_seam_fallback(ctx: FileContext, node: ast.Call) -> bool:
        """True for `rng if rng is not None else random.Random()` and
        `rng or random.Random()`: the injectable seam's default leg."""
        parent = ctx.parents.get(node)
        return isinstance(parent, (ast.IfExp, ast.BoolOp))
