"""host-sync-in-hot-path: the engine stage skeleton may only host-sync
in delivery.

The tick engines (solver/engine.py TickEngineBase and its resident
implementations) phase every tick through the stage skeleton — sweep,
drain, config, pack, staging, upload, solve — and the whole sub-100 ms
budget rests on those phases never blocking on the device: a
`.item()` / `block_until_ready()` / `jax.device_get()` /
`np.asarray(<device value>)` inside staging or solve serializes the
host against the solve it was supposed to overlap. Delivery ("download"
and "apply" laps) is where grants legitimately land on the host.

Statically we cannot always know a value is device-resident, so the
rule is anchored on the phase structure instead: inside any function
that laps a PhaseRecorder (`ph.lap("<phase>")`), statements are
attributed to the phase whose lap closes them (laps time the code
ABOVE them), and the listed sync constructs are flagged in every
segment except download/apply. Host-side numpy staging work is fine —
np.asarray on fresh host data is only flagged when its argument smells
device-sourced (a name bound from a solve/tick/device call) — while
`.item()`, `.block_until_ready()` and `jax.device_get()` have no
host-side reading and are flagged unconditionally.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from tools.lint.core import Checker, FileContext, Finding, RepoContext

SCOPE = ("doorman_tpu/solver/",)

DELIVERY_PHASES = {"download", "apply"}

# Unconditional device syncs.
_HARD_SYNC_ATTRS = {"block_until_ready", "item"}
_HARD_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
# Conditional: host conversions that sync when fed a device value.
_SOFT_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_SOFT_SYNC_NAMES = {"float", "bool", "int"}
# A name assigned from a call whose text mentions one of these is
# treated as device-sourced for the soft checks.
_DEVICE_SOURCES = ("solve", "pallas_call", "device_put", "_tick_fn", "dispatch")


def _lap_schedule(func: ast.AST) -> List[Tuple[int, str]]:
    laps = []
    for node in ast.walk(func):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("lap", "record")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            laps.append((node.lineno, node.args[0].value))
    laps.sort()
    return laps


def _phase_at(laps: List[Tuple[int, str]], lineno: int) -> Optional[str]:
    for lap_line, phase in laps:
        if lineno <= lap_line:
            return phase
    return None  # after the last lap: not a timed phase


class HostSyncInHotPath(Checker):
    name = "host-sync-in-hot-path"
    description = (
        "float()/bool()/.item()/np.asarray/block_until_ready on device "
        "values inside engine stage-skeleton phases other than delivery"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        if not ctx.relpath.startswith(SCOPE):
            return
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            laps = _lap_schedule(func)
            if not laps:
                continue
            device_names = self._device_sourced_names(func)
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                phase = _phase_at(laps, node.lineno)
                if phase is None or phase in DELIVERY_PHASES:
                    continue
                msg = self._sync_reason(node, device_names)
                if msg:
                    yield self.finding(
                        ctx, node,
                        f"{msg} in stage-skeleton phase {phase!r}: host "
                        "syncs belong in delivery (download/apply) — keep "
                        "this phase async against the device",
                    )

    @staticmethod
    def _device_sourced_names(func: ast.AST) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                txt = ast.unparse(node.value.func)
                if any(m in txt for m in _DEVICE_SOURCES):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            names.add(tgt.id)
                        elif isinstance(tgt, ast.Tuple):
                            names.update(
                                e.id for e in tgt.elts if isinstance(e, ast.Name)
                            )
        return names

    @staticmethod
    def _sync_reason(node: ast.Call, device_names: Set[str]) -> Optional[str]:
        txt = ast.unparse(node.func)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _HARD_SYNC_ATTRS:
            return f".{node.func.attr}() device sync"
        if txt in _HARD_SYNC_CALLS:
            return f"{txt}() device sync"
        arg_mentions_device = any(
            isinstance(n, ast.Name) and n.id in device_names
            for a in node.args for n in ast.walk(a)
        )
        if txt in _SOFT_SYNC_CALLS and arg_mentions_device:
            return f"{txt}() on a device-sourced value"
        if isinstance(node.func, ast.Name) and \
                node.func.id in _SOFT_SYNC_NAMES and arg_mentions_device:
            return f"{node.func.id}() on a device-sourced value"
        return None
