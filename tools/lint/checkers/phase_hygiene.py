"""trace-phase-hygiene: span and phase names come from the registries;
spans cannot leak.

Every telemetry surface in this repo joins on names: bench.py,
/debug/status, the flight recorder and the SLO engine all read the
phase vocabulary (solver/engine.py PHASES); trace consumers (Perfetto
overlays, test assertions, doc/observability.md's route tables) key on
span/instant names (obs/trace.py KNOWN_SPAN_NAMES / KNOWN_INSTANT_NAMES,
where `prefix.*` entries admit computed suffixes like
f"server.{method}"). A typo'd name doesn't fail — it silently records
into a stream nobody reads, which is why it is a lint rule and not a
runtime error.

Pairing: `tracer.span(...)` returns a context manager that must be
ENTERED — a span opened without `with` never closes and poisons
open-span accounting (Tracer.open_spans). The blessed shapes are the
`with` statement itself and the span-factory idiom (`return
tracer.span(...)` from a function whose name ends in `_span`, which
callers then enter). Everything else is an unmatched begin.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from tools.lint.core import Checker, FileContext, Finding, RepoContext

# The registries' own modules define the vocabulary; don't lint them
# against themselves.
_SELF_FILES = ("doorman_tpu/obs/trace.py", "doorman_tpu/obs/phases.py")


def _name_ok(name: str, registry: Set[str]) -> bool:
    if name in registry:
        return True
    return any(
        entry.endswith(".*") and name.startswith(entry[:-1])
        for entry in registry
    )


def _fstring_prefix(node: ast.JoinedStr) -> str:
    """Leading literal text of an f-string ('' when it starts with a
    placeholder)."""
    if node.values and isinstance(node.values[0], ast.Constant):
        return str(node.values[0].value)
    return ""


class TracePhaseHygiene(Checker):
    name = "trace-phase-hygiene"
    description = (
        "span/phase names must come from the obs registries; spans must "
        "be entered with `with` (or returned from a *_span factory)"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        if not ctx.relpath.startswith("doorman_tpu/"):
            return
        if ctx.relpath in _SELF_FILES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            attr = node.func.attr
            if attr == "lap" and repo.phases:
                yield from self._check_name(
                    ctx, node, repo.phases, "phase", "solver/engine.py PHASES"
                )
            elif attr == "span" and repo.span_names:
                yield from self._check_name(
                    ctx, node, repo.span_names, "span",
                    "obs/trace.py KNOWN_SPAN_NAMES",
                )
                yield from self._check_entered(ctx, node)
            elif attr == "instant" and repo.instant_names:
                yield from self._check_name(
                    ctx, node, repo.instant_names, "instant",
                    "obs/trace.py KNOWN_INSTANT_NAMES",
                )

    def _check_name(self, ctx, node, registry, kind, where) -> Iterator[Finding]:
        if not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if not _name_ok(name, registry):
                yield self.finding(
                    ctx, node,
                    f"{kind} name {name!r} is not in the registry ({where}): "
                    "unknown names record into streams no consumer reads — "
                    "add it to the registry or fix the typo",
                )
        elif isinstance(arg, ast.JoinedStr):
            prefix = _fstring_prefix(arg)
            if not prefix or not any(
                entry.endswith(".*") and prefix.startswith(entry[:-1])
                for entry in registry
            ):
                yield self.finding(
                    ctx, node,
                    f"computed {kind} name {ast.unparse(arg)} matches no "
                    f"`prefix.*` registry entry ({where})",
                )

    def _check_entered(self, ctx, node) -> Iterator[Finding]:
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.withitem):
            return
        if isinstance(parent, ast.Return):
            from tools.lint.core import enclosing_functions

            inner = enclosing_functions(ctx, node)
            if inner and inner[0].name.endswith("_span"):
                return
        yield self.finding(
            ctx, node,
            ".span(...) opened without `with`: the span never closes "
            "(unmatched begin). Enter it in a with-statement, or return "
            "it from a `*_span` factory the caller enters",
        )
