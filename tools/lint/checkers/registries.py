"""registry-coherence: multi-file registries cross-checked against use.

The repo's telemetry and contract registries are plain literals next to
the code they govern (engine PHASES, obs KNOWN_SPAN_NAMES /
KNOWN_INSTANT_NAMES, server FUSED_TRACKED_WRITERS), and the flight
recorder's Chrome overlay reads record fields by string key. The
forward direction is enforced (trace-phase-hygiene: every name used
must be registered); this rule machine-checks the REVERSE direction,
where today's drift is silent:

  * a ``PHASES`` entry no ``ph.lap("...")`` ever records — stale
    vocabulary every consumer (bench, SLO engine, flight recorder)
    still budgets for;
  * a concrete ``KNOWN_SPAN_NAMES`` / ``KNOWN_INSTANT_NAMES`` entry no
    ``.span("...")`` / ``.instant("...")`` ever opens (wildcard
    ``prefix.*`` entries are checked against computed f-string
    prefixes too) — a route table documenting telemetry that does not
    exist;
  * a ``FUSED_TRACKED_WRITERS`` entry whose ``Class.method`` no longer
    exists in the tree — an audited exemption pointing at nothing;
  * a field the flight recorder's overlay READS (``rec.get("k")``,
    or ``for k in ("a", "b"): ... rec[k]``) that no producer ever
    WRITES (``record(k=...)`` keywords, or ``rec["k"] = ...`` stores
    in a function that ends in ``record(**rec)``) — a dashboard lane
    that will never light up.

Findings land on the registry entry's own line (or the stale read), so
the fix is local: delete the entry, or re-wire the producer and keep
it. Suppress with ``# doorman: allow[registry-coherence] <reason>`` on
the entry line for vocabulary that is intentionally ahead of the code
(e.g. a wire format the next PR starts emitting).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.lint.core import (
    Checker,
    FileContext,
    Finding,
    RepoContext,
    _REGISTRY_NAMES,
)

_FLIGHTREC_FILE = "doorman_tpu/obs/flightrec.py"
# record() itself stamps seq; `t` is the time axis every producer sets.
_FLIGHTREC_IMPLICIT = {"seq"}


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fstring_prefix(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.JoinedStr) and node.values and \
            isinstance(node.values[0], ast.Constant):
        return str(node.values[0].value)
    return None


class _Usage:
    """Repo-wide mined usage: names recorded/opened, flightrec fields."""

    def __init__(self, repo: RepoContext):
        self.laps: Set[str] = set()
        self.spans: Set[str] = set()
        self.span_prefixes: Set[str] = set()
        self.instants: Set[str] = set()
        self.instant_prefixes: Set[str] = set()
        self.flightrec_writes: Set[str] = set()
        self.flightrec_reads: Dict[str, ast.AST] = {}
        for ctx in repo.files:
            self._mine(ctx)

    def _mine(self, ctx: FileContext) -> None:
        # Dicts that are splatted into a .record(**rec) call anywhere in
        # this file: their string-subscript stores are producer writes.
        splat_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr == "record":
                for kw in node.keywords:
                    if kw.arg:
                        self.flightrec_writes.add(kw.arg)
                    elif isinstance(kw.value, ast.Name):
                        splat_names.add(kw.value.id)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript) and isinstance(
                            tgt.value, ast.Name) and \
                            tgt.value.id in splat_names:
                        key = _const_str(tgt.slice)
                        if key:
                            self.flightrec_writes.add(key)
                # rec[key] = ... inside `for key in ("a", "b"):`
            elif isinstance(node, ast.For):
                keys = self._loop_keys(node)
                if keys and self._loop_subscripts(node, splat_names):
                    self.flightrec_writes.update(keys)
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                attr = node.func.attr
                first = node.args[0] if node.args else None
                if attr == "lap" and first is not None:
                    name = _const_str(first)
                    if name:
                        self.laps.add(name)
                elif attr == "record" and first is not None:
                    # PhaseRecorder.record(phase, seconds): positional
                    # string first arg (flightrec.record is kw-only).
                    name = _const_str(first)
                    if name:
                        self.laps.add(name)
                elif attr == "span" and first is not None:
                    name = _const_str(first)
                    if name:
                        self.spans.add(name)
                    prefix = _fstring_prefix(first)
                    if prefix:
                        self.span_prefixes.add(prefix)
                elif attr == "instant" and first is not None:
                    name = _const_str(first)
                    if name:
                        self.instants.add(name)
                    prefix = _fstring_prefix(first)
                    if prefix:
                        self.instant_prefixes.add(prefix)

        if ctx.relpath == _FLIGHTREC_FILE:
            self._mine_reads(ctx)

    @staticmethod
    def _loop_keys(node: ast.For) -> Optional[List[str]]:
        if not isinstance(node.iter, (ast.Tuple, ast.List)):
            return None
        keys = []
        for elt in node.iter.elts:
            s = _const_str(elt)
            if s is None:
                return None
            keys.append(s)
        return keys

    @staticmethod
    def _loop_subscripts(node: ast.For, splat_names: Set[str]) -> bool:
        if not isinstance(node.target, ast.Name):
            return False
        var = node.target.id
        for n in ast.walk(node):
            if isinstance(n, ast.Subscript) and isinstance(
                    n.slice, ast.Name) and n.slice.id == var and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id in splat_names:
                return True
        return False

    def _mine_reads(self, ctx: FileContext) -> None:
        """String keys the flight recorder pulls out of records."""
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr == "get" \
                    and node.args:
                key = _const_str(node.args[0])
                if key:
                    self.flightrec_reads.setdefault(key, node)
            elif isinstance(node, ast.For):
                keys = self._loop_keys(node)
                if not keys or not isinstance(node.target, ast.Name):
                    continue
                var = node.target.id
                uses_var_key = any(
                    (isinstance(n, ast.Subscript)
                     and isinstance(n.slice, ast.Name)
                     and n.slice.id == var)
                    or (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "get" and n.args
                        and isinstance(n.args[0], ast.Name)
                        and n.args[0].id == var)
                    for n in ast.walk(node)
                )
                if uses_var_key:
                    for k in keys:
                        self.flightrec_reads.setdefault(k, node)


class RegistryCoherence(Checker):
    name = "registry-coherence"
    description = (
        "registry entries cross-checked against real use: stale PHASES "
        "/ span / instant names, ghost FUSED_TRACKED_WRITERS entries, "
        "flightrec fields read but never recorded"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        analysis = repo.cache.get(self.name)
        if analysis is None:
            analysis = self._analyze(repo)
            repo.cache[self.name] = analysis
        for f in analysis.get(ctx.relpath, ()):
            yield f

    def _analyze(self, repo: RepoContext) -> Dict[str, List[Finding]]:
        use = _Usage(repo)
        findings: Dict[str, List[Finding]] = {}

        def emit(ctx: FileContext, node: ast.AST, message: str) -> None:
            findings.setdefault(ctx.relpath, []).append(
                self.finding(ctx, node, message)
            )

        for ctx in repo.files:
            for name, elt, value in self._registry_entries(ctx):
                if name == "PHASES":
                    if value not in use.laps:
                        emit(ctx, elt,
                             f"PHASES entry {value!r} is never lapped "
                             "(no ph.lap/record call records it): stale "
                             "vocabulary — delete it or wire the phase",
                             )
                elif name == "KNOWN_SPAN_NAMES":
                    self._check_obs_entry(
                        emit, ctx, elt, value, "span",
                        use.spans, use.span_prefixes,
                    )
                elif name == "KNOWN_INSTANT_NAMES":
                    self._check_obs_entry(
                        emit, ctx, elt, value, "instant",
                        use.instants, use.instant_prefixes,
                    )
                elif name == "FUSED_TRACKED_WRITERS":
                    if not repo.graph.has_qualname(value):
                        emit(ctx, elt,
                             f"FUSED_TRACKED_WRITERS entry {value!r} "
                             "names no function in the tree: the "
                             "audited exemption points at nothing — "
                             "remove it (or fix the qualname)",
                             )

        fr_ctx = repo.by_path.get(_FLIGHTREC_FILE)
        if fr_ctx is not None:
            for key, node in sorted(use.flightrec_reads.items()):
                if key in _FLIGHTREC_IMPLICIT or \
                        key in use.flightrec_writes:
                    continue
                emit(fr_ctx, node,
                     f"flight-recorder overlay reads field {key!r} "
                     "but no producer ever records it (no record("
                     f"{key}=...) and no rec[{key!r}] = ... feeding a "
                     "record(**...) call): dead dashboard lane",
                     )
        return findings

    @staticmethod
    def _check_obs_entry(emit, ctx, elt, value, kind, used, prefixes):
        if value.endswith(".*"):
            stem = value[:-1]  # "server." from "server.*"
            if not any(p.startswith(stem) for p in prefixes) and \
                    not any(u.startswith(stem) for u in used):
                emit(ctx, elt,
                     f"wildcard {kind} registry entry {value!r} matches "
                     f"no opened {kind} and no computed f\"{stem}"
                     "{...}\" name: stale vocabulary",
                     )
        elif value not in used:
            emit(ctx, elt,
                 f"{kind} registry entry {value!r} is never opened "
                 f"(no .{kind}({value!r}) anywhere): stale vocabulary "
                 "— delete it or wire the emitter",
                 )

    @staticmethod
    def _registry_entries(ctx: FileContext
                          ) -> Iterator[Tuple[str, ast.AST, str]]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            if name not in _REGISTRY_NAMES:
                continue
            value = node.value
            if isinstance(value, ast.Call) and isinstance(
                    value.func, ast.Name) and value.func.id in (
                        "frozenset", "set") and len(value.args) == 1:
                value = value.args[0]
            if not isinstance(value, (ast.Tuple, ast.List, ast.Set)):
                continue
            for elt in value.elts:
                s = _const_str(elt)
                if s is not None:
                    yield name, elt, s
