"""lock-order: whole-program lock-acquisition analysis.

PRs 9-10 made the server genuinely concurrent — stream outbound queues
drained by gRPC handler tasks, the PipelinedTicker straddling the event
loop and the tick executor, the federation reconcile beat running
between processes — and the per-file lock-discipline rule cannot see
the two bug classes that concurrency actually ships:

  * **ordering cycles** — thread 1 holds ``A._lock`` and calls into a
    function that takes ``B._lock``; thread 2 does the reverse. Each
    file looks fine; the deadlock lives in the call graph.
  * **blocking under a lock** — a gRPC call, ``Future.result()``, a
    bounded ``queue.put`` (the 256-deep stream queues), ``time.sleep``
    or a device sync executed while a lock is held turns every other
    user of that lock into a hostage of the slow operation.

Mechanics, all on the tools/lint/graph.py substrate:

  * lock identity is class-scoped: ``self._lock`` inside class ``C``
    is the node ``C._lock``; module globals are ``<module>._lock``.
    Only KNOWN locks count — attributes assigned
    ``threading.Lock/RLock/Condition()`` anywhere in the tree, plus
    anything named by ``# guarded-by:`` / ``# holds-lock:`` markers —
    so ``with tracer.span(...)`` and friends never register;
  * held sets propagate lexically (``with`` nesting, the existing
    ``# holds-lock:`` def annotation) and interprocedurally: a call
    made while holding H adds edges H x acquires*(callee), where
    acquires* is a fixed point over the approximate call graph;
  * edges feed a digraph; any strongly-connected component with two or
    more locks is reported ONCE (at its first edge site, naming the
    full cycle), so one ``# doorman: allow[lock-order]`` with a reason
    retires one cycle;
  * blocking operations are classified syntactically (sleep, gRPC
    stubs, ``.result()``, ``put`` on attributes assigned a BOUNDED
    queue, ``wait`` on mined Condition/Event attributes, device syncs)
    and propagate the same way, so a lock held across a call whose
    callee's callee blocks is still caught.

Class-scoped identity merges instances: two DIFFERENT objects of one
class can interleave ``C._lock`` without deadlock, and a re-acquisition
is only reported when the spelling pins the same object (``self.X``
taken twice on a non-reentrant Lock). Cross-instance cycles through two
classes are real regardless of instance identity, which is why the
merge is the right default for the cycle rule.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.lint.core import (
    Checker,
    FileContext,
    Finding,
    RepoContext,
    enclosing_class,
)

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}
_REENTRANT = {"threading.RLock", "RLock"}
_QUEUE_CTORS = {
    "queue.Queue", "asyncio.Queue", "Queue", "queue.LifoQueue",
    "queue.PriorityQueue",
}
_WAITABLE_CTORS = {
    "threading.Condition", "threading.Event", "Condition", "Event",
    "asyncio.Event",
}
# Dotted call names that block unconditionally.
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep()",
    "jax.device_get": "jax.device_get() device sync",
    "jax.block_until_ready": "jax.block_until_ready() device sync",
}
_BLOCKING_ATTRS = {
    "result": "Future.result()",
    "block_until_ready": ".block_until_ready() device sync",
    "item": ".item() device sync",
}


def _ctor_name(node: ast.AST) -> str:
    if isinstance(node, ast.Call):
        try:
            return ast.unparse(node.func)
        except Exception:  # pragma: no cover
            return ""
    return ""


class _Locks:
    """Repo-wide mined lock/queue/waitable vocabulary."""

    def __init__(self, repo: RepoContext):
        # (class name | module dotted, attr) -> reentrant?
        self.locks: Dict[Tuple[str, str], bool] = {}
        self.attr_owners: Dict[str, Set[str]] = {}  # attr -> owner set
        self.bounded_queue_attrs: Set[str] = set()
        self.waitable_attrs: Set[str] = set()
        for ctx in repo.files:
            self._mine(ctx)
        # `# guarded-by:` / `# holds-lock:` markers name locks that may
        # have no visible constructor (fixtures, injected locks).
        for ctx in repo.files:
            self._mine_markers(ctx)

    def _module_id(self, ctx: FileContext) -> str:
        mod = ctx.relpath[:-3].replace("/", ".")
        return mod[:-9] if mod.endswith(".__init__") else mod

    def _mine(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            ctor = _ctor_name(value)
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            if ctor in _LOCK_CTORS:
                for tgt in targets:
                    owner, attr = self._owner_attr(ctx, tgt)
                    if owner is None or attr is None:
                        continue
                    self.locks[(owner, attr)] = ctor in _REENTRANT
                    self.attr_owners.setdefault(attr, set()).add(owner)
            if ctor in _QUEUE_CTORS and self._is_bounded(value):
                for tgt in targets:
                    _, attr = self._owner_attr(ctx, tgt)
                    if attr:
                        self.bounded_queue_attrs.add(attr)
            if ctor in _WAITABLE_CTORS:
                for tgt in targets:
                    _, attr = self._owner_attr(ctx, tgt)
                    if attr:
                        self.waitable_attrs.add(attr)

    @staticmethod
    def _is_bounded(call: ast.AST) -> bool:
        if not isinstance(call, ast.Call):
            return False
        for kw in call.keywords:
            if kw.arg == "maxsize":
                if isinstance(kw.value, ast.Constant):
                    return bool(kw.value.value)
                return True  # maxsize=VAR: assume bounded
        if call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Constant):
                return bool(arg.value)
            return True
        return False

    def _owner_attr(self, ctx: FileContext, tgt: ast.AST
                    ) -> Tuple[Optional[str], Optional[str]]:
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            cls = enclosing_class(ctx, tgt)
            return (cls.name if cls else None), tgt.attr
        if isinstance(tgt, ast.Name):
            return self._module_id(ctx), tgt.id
        return None, None

    def _mine_markers(self, ctx: FileContext) -> None:
        import re

        marker = re.compile(
            r"#\s*(?:guarded-by|holds-lock):\s*([A-Za-z_][A-Za-z0-9_.]*)"
        )
        for text in ctx.lines:
            m = marker.search(text)
            if not m:
                continue
            attr = m.group(1).rsplit(".", 1)[-1]
            if not any(attr == a for (_, a) in self.locks):
                self.attr_owners.setdefault(attr, set())

    # -- canonicalization ----------------------------------------------

    def canon(self, ctx: FileContext, expr: ast.AST,
              cls: Optional[str]) -> Optional[str]:
        """Canonical lock id of a with-item / annotation expression, or
        None when it is not a known lock."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if (attr not in self.attr_owners
                    and not any(attr == a for (_, a) in self.locks)):
                return None
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                owner = cls or "?"
                return f"{owner}.{attr}"
            owners = {
                o for (o, a) in self.locks if a == attr
            } | self.attr_owners.get(attr, set())
            if len(owners) == 1:
                return f"{next(iter(owners))}.{attr}"
            return f"*.{attr}"
        if isinstance(expr, ast.Name):
            mod = self._module_id(ctx)
            if (mod, expr.id) in self.locks:
                return f"{mod}.{expr.id}"
        return None

    def canon_text(self, ctx: FileContext, text: str,
                   cls: Optional[str]) -> Optional[str]:
        try:
            expr = ast.parse(text, mode="eval").body
        except SyntaxError:
            return None
        return self.canon(ctx, expr, cls)

    def reentrant(self, lock_id: str) -> bool:
        owner, _, attr = lock_id.rpartition(".")
        return self.locks.get((owner, attr), False)


class _FnFacts:
    """Per-function lexical facts for the fixed points."""

    __slots__ = ("acquired", "edges", "calls", "blocking", "acq_site")

    def __init__(self):
        self.acquired: Set[str] = set()
        # (src, dst, node, dst_text)
        self.edges: List[Tuple[str, str, ast.AST, str]] = []
        # (call node, frozenset held, targets, held_texts)
        self.calls: List[tuple] = []
        # (node, desc, frozenset held)
        self.blocking: List[Tuple[ast.AST, str, frozenset]] = []
        self.acq_site: Dict[str, ast.AST] = {}


class LockOrder(Checker):
    name = "lock-order"
    description = (
        "call-graph-propagated lock acquisition: ordering cycles "
        "(potential deadlocks) and blocking calls under a held lock"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        analysis = repo.cache.get(self.name)
        if analysis is None:
            analysis = self._analyze(repo)
            repo.cache[self.name] = analysis
        for f in analysis.get(ctx.relpath, ()):
            yield f

    # -- whole-program pass --------------------------------------------

    def _analyze(self, repo: RepoContext) -> Dict[str, List[Finding]]:
        graph = repo.graph
        locks = _Locks(repo)
        facts: Dict[Tuple[str, str], _FnFacts] = {}
        for fn in graph.functions.values():
            facts[fn.key] = self._lexical(fn, locks)

        acq = self._fixed_point(
            graph, {k: set(f.acquired) for k, f in facts.items()},
            lambda f: f.calls,
            facts,
        )
        block = self._block_fixed_point(graph, facts)

        findings: Dict[str, List[Finding]] = {}

        def emit(ctx: FileContext, node: ast.AST, message: str) -> None:
            findings.setdefault(ctx.relpath, []).append(
                self.finding(ctx, node, message)
            )

        # Edge set: lexical + interprocedural.
        edges: Dict[Tuple[str, str], Tuple[FileContext, ast.AST, str]] = {}
        for fn in graph.functions.values():
            f = facts[fn.key]
            for src, dst, node, _ in f.edges:
                edges.setdefault((src, dst), (fn.ctx, node, fn.qualname))
            for call, held, targets, _ in f.calls:
                deep: Set[str] = set()
                for t in targets:
                    deep |= acq.get(t.key, set())
                for h in held:
                    for l in deep:
                        if l != h:
                            edges.setdefault(
                                (h, l), (fn.ctx, call, fn.qualname)
                            )
        # Re-acquisition of a non-reentrant lock pinned to one object.
        for fn in graph.functions.values():
            f = facts[fn.key]
            for src, dst, node, dst_text in f.edges:
                if src == dst and not locks.reentrant(src) and \
                        dst_text.startswith("self."):
                    emit(fn.ctx, node,
                         f"{dst_text} ({src}) is acquired while already "
                         "held by this function: a non-reentrant Lock "
                         "self-deadlocks here",
                         )
            for call, held, targets, _ in f.calls:
                for t in targets:
                    again = held & acq.get(t.key, set())
                    for l in again:
                        if locks.reentrant(l):
                            continue
                        if not (isinstance(call.func, ast.Attribute)
                                and isinstance(call.func.value, ast.Name)
                                and call.func.value.id == "self"):
                            continue
                        if not l.startswith(f"{fn.cls}."):
                            continue
                        emit(fn.ctx, call,
                             f"calls {t.qualname}() while holding {l}, "
                             f"and {t.qualname} acquires {l} again: a "
                             "non-reentrant Lock self-deadlocks "
                             "(annotate the callee with # holds-lock: "
                             "or narrow this critical section)",
                             )

        # Ordering cycles: one finding per SCC of the lock digraph.
        adj: Dict[str, Set[str]] = {}
        for (src, dst) in edges:
            if src != dst:
                adj.setdefault(src, set()).add(dst)
                adj.setdefault(dst, set())
        for scc in self._sccs(adj):
            if len(scc) < 2:
                continue
            scc_set = set(scc)
            sites = sorted(
                (
                    (ectx.relpath, node.lineno, ectx, node, src, dst, qn)
                    for (src, dst), (ectx, node, qn) in edges.items()
                    if src in scc_set and dst in scc_set
                ),
                key=lambda t: (t[0], t[1]),
            )
            if not sites:
                continue
            _, _, ectx, node, src, dst, qn = sites[0]
            others = "; ".join(
                f"{s}->{d} at {p}:{ln}" for p, ln, _, _, s, d, _ in sites[1:]
            ) or "same-function nesting"
            emit(ectx, node,
                 f"lock-order cycle {{{', '.join(sorted(scc_set))}}}: "
                 f"{qn} acquires {dst} while holding {src}, but the "
                 f"reverse order also exists ({others}) — two threads "
                 "taking these locks in opposite orders deadlock; pick "
                 "one global order (doc/lint.md lock-order)",
                 )

        # Blocking under a lock.
        for fn in graph.functions.values():
            f = facts[fn.key]
            for node, desc, held in f.blocking:
                if not held:
                    continue
                locks_txt = ", ".join(sorted(held))
                emit(fn.ctx, node,
                     f"{desc} while holding {locks_txt}: every other "
                     "user of the lock now waits on this blocking "
                     "operation — move it outside the critical section",
                     )
            for call, held, targets, _ in f.calls:
                if not held:
                    continue
                for t in targets:
                    for desc, origin in sorted(block.get(t.key, set())):
                        locks_txt = ", ".join(sorted(held))
                        emit(fn.ctx, call,
                             f"calls {t.qualname}() while holding "
                             f"{locks_txt}, and it reaches {desc} (in "
                             f"{origin}): the lock is held across a "
                             "blocking operation",
                             )
        return findings

    # -- lexical facts --------------------------------------------------

    def _lexical(self, fn, locks: _Locks) -> _FnFacts:
        f = _FnFacts()
        ctx, func, cls = fn.ctx, fn.node, fn.cls
        entry: Set[str] = set()
        marker = ctx.holds_marker(func)
        if marker:
            held0 = locks.canon_text(ctx, marker, cls)
            if held0:
                entry.add(held0)

        def visit(node: ast.AST, held: Set[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not func:
                return  # separate call-graph node; no lexical inherit
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in node.items:
                    visit(item.context_expr, inner)
                    lock_id = locks.canon(ctx, item.context_expr, cls)
                    if lock_id is None:
                        continue
                    try:
                        txt = ast.unparse(item.context_expr)
                    except Exception:  # pragma: no cover
                        txt = lock_id
                    f.acquired.add(lock_id)
                    f.acq_site.setdefault(lock_id, node)
                    for h in inner:
                        f.edges.append((h, lock_id, node, txt))
                    inner = inner | {lock_id}
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Call):
                desc = self._blocking_desc(node, locks)
                if desc:
                    f.blocking.append((node, desc, frozenset(held)))
                f.calls.append((node, frozenset(held), (), ()))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(func, entry)
        # Resolve call targets through the graph (fn.calls was built by
        # RepoGraph; join on the call node identity).
        resolved = {id(c): targets for c, targets in fn.calls}
        f.calls = [
            (c, held, resolved.get(id(c), ()), ())
            for (c, held, _, _) in f.calls
        ]
        return f

    @staticmethod
    def _blocking_desc(call: ast.Call, locks: _Locks) -> Optional[str]:
        func = call.func
        try:
            txt = ast.unparse(func)
        except Exception:  # pragma: no cover
            txt = ""
        if txt in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[txt]
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr in _BLOCKING_ATTRS:
                return _BLOCKING_ATTRS[attr]
            recv = func.value
            recv_attr = None
            if isinstance(recv, ast.Attribute):
                recv_attr = recv.attr
            elif isinstance(recv, ast.Name):
                recv_attr = recv.id
            if attr == "put" and recv_attr in locks.bounded_queue_attrs:
                return f"bounded queue.put on {recv_attr!r}"
            if attr == "wait" and recv_attr in locks.waitable_attrs:
                return f".wait() on {recv_attr!r}"
            if recv_attr and recv_attr.lower().endswith("stub"):
                return f"gRPC call {txt}()"
        return None

    # -- fixed points ---------------------------------------------------

    @staticmethod
    def _fixed_point(graph, init, calls_of, facts):
        acq = init
        for _ in range(32):
            changed = False
            for fn in graph.functions.values():
                cur = acq[fn.key]
                add: Set[str] = set()
                for _, _, targets, _ in facts[fn.key].calls:
                    for t in targets:
                        add |= acq.get(t.key, set())
                if not add <= cur:
                    acq[fn.key] = cur | add
                    changed = True
            if not changed:
                break
        return acq

    def _block_fixed_point(self, graph, facts):
        block: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {
            fn.key: {
                (desc, fn.qualname)
                for _, desc, _ in facts[fn.key].blocking
            }
            for fn in graph.functions.values()
        }
        for _ in range(32):
            changed = False
            for fn in graph.functions.values():
                cur = block[fn.key]
                add: Set[Tuple[str, str]] = set()
                for _, held, targets, _ in facts[fn.key].calls:
                    for t in targets:
                        add |= block.get(t.key, set())
                if not add <= cur:
                    block[fn.key] = cur | add
                    changed = True
            if not changed:
                break
        return block

    @staticmethod
    def _sccs(adj: Dict[str, Set[str]]) -> List[List[str]]:
        """Iterative Tarjan."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        for root in sorted(adj):
            if root in index:
                continue
            work = [(root, iter(sorted(adj.get(root, ()))))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    out.append(scc)
        return out
