"""jit-closure-capture: the PR-4 pallas regression class, machine-checked.

Six tier-1 tests failed for five PR rounds because `solve_lanes`
compared a device column against `AlgoKind.FAIR_SHARE` directly: an
IntEnum operand becomes a strong-typed int64 scalar constant under
tracing, and a pallas kernel body rejects any non-ref closure constant
(and even under plain jit the int64 const flips weak-typed arithmetic).
The fix is one character-cheap seam — `int(kind)` keeps the operand a
weak-typed Python literal — but nothing enforced it; this checker does.

Scope: device-code functions in solver/ and parallel/ modules — a
function is device code when it

  * is decorated with jit (`@jax.jit`, `@partial(jax.jit, ...)`), or
  * is (or is nested in) a pallas kernel: passed to `pl.pallas_call` /
    `pallas_call`, or named `kernel` / `*_kernel`, or
  * references `jnp.` / `jax.lax` in its body (lane math that gets
    inlined into kernels, exactly like solve_lanes was).

Inside such functions, any comparison or arithmetic whose operand is a
bare `<IntEnumClass>.<MEMBER>` attribute is flagged unless the operand
is wrapped in `int(...)`. IntEnum classes are discovered from the
scanned tree (class X(enum.IntEnum)), so new enums are covered the day
they are written.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from tools.lint.core import (
    Checker,
    FileContext,
    Finding,
    RepoContext,
    enclosing_functions,
)

SCOPE = ("doorman_tpu/solver/", "doorman_tpu/parallel/")

_DEVICE_NAME_MARKS = ("jnp", "pl")


def _is_jit_decorated(func: ast.AST) -> bool:
    for dec in getattr(func, "decorator_list", []):
        txt = ast.unparse(dec)
        if "jit" in txt.split("(")[0] or "jax.jit" in txt:
            return True
    return False


def _kernel_names(tree: ast.AST) -> Set[str]:
    """Names of functions passed (positionally first) to pallas_call."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = ast.unparse(node.func)
            if fname.endswith("pallas_call") and node.args and \
                    isinstance(node.args[0], ast.Name):
                out.add(node.args[0].id)
    return out


def _references_device_api(func: ast.AST) -> bool:
    for node in ast.walk(func):
        if isinstance(node, ast.Name) and node.id in _DEVICE_NAME_MARKS:
            return True
        if isinstance(node, ast.Attribute):
            txt = ast.unparse(node)
            if txt.startswith(("jax.lax", "jnp.", "pl.")):
                return True
    return False


class JitClosureCapture(Checker):
    name = "jit-closure-capture"
    description = (
        "IntEnum members closed over in pallas kernels / jitted solve "
        "functions must pass through int() (the PR-4 regression class)"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        if not ctx.relpath.startswith(SCOPE):
            return
        enums = repo.int_enum_classes
        if not enums:
            return
        kernels = _kernel_names(ctx.tree)
        device_fns = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if (
                _is_jit_decorated(node)
                or node.name in kernels
                or node.name == "kernel"
                or node.name.endswith("_kernel")
                or _references_device_api(node)
            ):
                device_fns.append(node)
        for func in device_fns:
            yield from self._check_function(ctx, func, enums, device_fns)

    def _check_function(self, ctx, func, enums, device_fns):
        for node in ast.walk(func):
            # Attribute nodes reached through a *nested* device fn are
            # reported once, at the innermost device function.
            inner = enclosing_functions(ctx, node)
            if inner and inner[0] is not func and inner[0] in device_fns:
                continue
            operands = []
            if isinstance(node, ast.Compare):
                operands = [node.left, *node.comparators]
            elif isinstance(node, ast.BinOp):
                operands = [node.left, node.right]
            for op in operands:
                enum_txt = self._bare_enum_member(op, enums)
                if enum_txt is not None:
                    yield self.finding(
                        ctx, op,
                        f"{enum_txt} used as a traced operand: an IntEnum "
                        "materializes a strong-typed int64 closure const "
                        "that pallas kernels reject (PR-4 regression "
                        f"class); wrap it as int({enum_txt})",
                    )

    @staticmethod
    def _bare_enum_member(node: ast.AST, enums) -> "str | None":
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in enums
        ):
            return ast.unparse(node)
        return None
