"""device-sync-taint: host syncs REACHABLE from a hot phase, plus
donation safety.

host-sync-in-hot-path sees a sync typed literally inside a
PhaseRecorder-lapped segment. This rule upgrades the ROADMAP's
fused-tick gate from "syncs typed inside the phase" to "syncs reachable
from the phase": device values are tracked through assignments, returns
and calls (tools/lint/dataflow.py), and a ``float()`` three helpers
deep is attributed back to the tick phase that can reach it.

  * **sources** — results of ``jnp.*`` / ``jax.*`` / ``lax.*`` /
    ``pl.*`` calls; taint survives arithmetic, indexing, method calls
    on a tainted receiver (``x.sum()``), tuple packing/unpacking, and
    function returns (a helper returning a ``jnp`` expression taints
    its callers' results, via a call-graph fixed point);
  * **sinks** — implicit host syncs: ``float()/bool()/int()``,
    ``.item()``, ``.tolist()``, ``np.asarray/np.array``,
    ``jax.device_get``, ``.block_until_ready()``, branching on a
    tainted value, and iterating one;
  * **hot region** — call sites inside a lap-recording function's
    non-delivery segments (same phase attribution as
    host-sync-in-hot-path: a lap times the code above it; download /
    apply are delivery) are roots; everything they can reach through
    the approximate call graph is hot. Sinks in hot code are findings;
    tainted arguments crossing into a callee parameter that sinks
    inside the callee are reported at the call site (that's where the
    device value escaped);
  * **division of labor** — direct sinks lexically inside a
    ``solver/`` lap function stay host-sync-in-hot-path findings (the
    per-file rule already anchors them to an exact phase); this rule
    reports everything the per-file rule cannot see: helpers, other
    packages' lap functions (federation/aggregate.py), and the
    call-crossing cases.

Donation safety rides along: a function jitted with a literal
``donate_argnums`` invalidates the donated arguments — referencing a
donated name after the donating call (without rebinding it, as in
``a, b = step(a, b)``) reads freed device memory and is flagged
regardless of phase.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.lint.core import Checker, FileContext, Finding, RepoContext
from tools.lint.checkers.host_sync import DELIVERY_PHASES, _lap_schedule, _phase_at
from tools.lint.dataflow import DEVICE, FunctionTaint

_SOURCE_PREFIXES = ("jnp.", "jax.", "lax.", "pl.", "pltpu.")
# jax-namespace calls whose result is a host value (or no value):
# naming them sources would taint strings and dtypes.
_NOT_SOURCES = {
    "jax.device_get", "jax.block_until_ready", "jax.debug.print",
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.default_backend", "jax.process_index",
    "jax.process_count", "jnp.dtype", "jnp.issubdtype", "jnp.result_type",
    "jnp.shape", "jnp.ndim", "jax.eval_shape", "jax.tree_util.tree_map",
}
_MAX_SUMMARY_PASSES = 8


def _param_names(func: ast.AST) -> List[str]:
    a = func.args
    names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
    return names


class DeviceSyncTaint(Checker):
    name = "device-sync-taint"
    description = (
        "device values tracked through calls: implicit host syncs "
        "reachable from hot tick phases, and donated buffers used "
        "after donation"
    )

    def run(self, ctx: FileContext, repo: RepoContext) -> Iterator[Finding]:
        analysis = repo.cache.get(self.name)
        if analysis is None:
            analysis = self._analyze(repo)
            repo.cache[self.name] = analysis
        for f in analysis.get(ctx.relpath, ()):
            yield f

    # -- whole-program pass --------------------------------------------

    def _analyze(self, repo: RepoContext) -> Dict[str, List[Finding]]:
        graph = repo.graph
        findings: Dict[str, List[Finding]] = {}

        def emit(ctx: FileContext, node: ast.AST, message: str) -> None:
            findings.setdefault(ctx.relpath, []).append(
                self.finding(ctx, node, message)
            )

        # ---- interprocedural taint summaries (fixed point) ----
        summaries: Dict[tuple, dict] = {
            fn.key: {"returns_device": False, "sink_params": {}}
            for fn in graph.functions.values()
        }

        def is_source(call: ast.Call) -> bool:
            try:
                txt = ast.unparse(call.func)
            except Exception:  # pragma: no cover
                return False
            if txt in _NOT_SOURCES:
                return False
            return txt.startswith(_SOURCE_PREFIXES)

        # Engine device tables: self-attributes assigned from a device
        # source anywhere in their class are device-origin at every
        # read (the resident solvers' permanently-device-resident
        # grants/wants tables).
        device_attrs: Dict[Tuple[str, str], Set[str]] = {}
        for fn in graph.functions.values():
            if fn.cls is None:
                continue
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                sourced = any(
                    isinstance(n, ast.Call) and is_source(n)
                    for n in ast.walk(node.value)
                )
                if not sourced:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and isinstance(
                            tgt.value, ast.Name) and tgt.value.id == "self":
                        device_attrs.setdefault(
                            (fn.ctx.relpath, fn.cls), set()
                        ).add(tgt.attr)

        def make_is_device_attr(fn):
            if fn.cls is None:
                return None
            attrs = device_attrs.get((fn.ctx.relpath, fn.cls))
            if not attrs:
                return None

            def is_device_attr(node: ast.Attribute) -> bool:
                return (isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr in attrs)

            return is_device_attr

        call_targets: Dict[tuple, Dict[int, tuple]] = {
            fn.key: {id(c): targets for c, targets in fn.calls}
            for fn in graph.functions.values()
        }

        def make_oracles(fn):
            resolved = call_targets[fn.key]

            def targets_of(fn_, call: ast.Call):
                return resolved.get(id(call), ())

            def returns_device(call: ast.Call) -> bool:
                return any(
                    summaries[t.key]["returns_device"]
                    for t in targets_of(fn, call)
                )

            def sink_for_arg(call: ast.Call, arg) -> Optional[tuple]:
                for t in targets_of(fn, call):
                    sp = summaries[t.key]["sink_params"]
                    if not sp:
                        continue
                    if isinstance(arg, int):
                        params = _param_names(t.node)
                        if t.cls is not None and params[:1] == ["self"]:
                            params = params[1:]
                        if arg < len(params) and params[arg] in sp:
                            reason, _ = sp[params[arg]]
                            return reason, (t.qualname, t.ctx.relpath)
                    elif arg in sp:
                        reason, _ = sp[arg]
                        return reason, (t.qualname, t.ctx.relpath)
                return None

            return returns_device, sink_for_arg

        taints: Dict[tuple, FunctionTaint] = {}
        for _ in range(_MAX_SUMMARY_PASSES):
            changed = False
            for fn in graph.functions.values():
                returns_device, sink_for_arg = make_oracles(fn)
                ft = FunctionTaint(
                    fn.node,
                    is_source=is_source,
                    returns_device=returns_device,
                    sink_for_arg=sink_for_arg,
                    is_device_attr=make_is_device_attr(fn),
                ).run()
                taints[fn.key] = ft
                s = summaries[fn.key]
                rd = DEVICE in ft.returns
                if rd and not s["returns_device"]:
                    s["returns_device"] = True
                    changed = True
                for ev in ft.events:
                    for origin in ev.origins:
                        if origin == DEVICE or origin not in ft.param_names:
                            continue
                        if origin not in s["sink_params"]:
                            s["sink_params"][origin] = (
                                ev.reason, fn.qualname
                            )
                            changed = True
            if not changed:
                break

        # ---- hot region ----
        lap_fns = {}
        for fn in graph.functions.values():
            laps = _lap_schedule(fn.node)
            if laps:
                lap_fns[fn.key] = laps
        hot_roots = []
        for key, laps in lap_fns.items():
            fn = graph.functions[key]
            for call, targets in fn.calls:
                phase = _phase_at(laps, call.lineno)
                if phase is None or phase in DELIVERY_PHASES:
                    continue
                hot_roots.extend(targets)
        hot = graph.transitive_callees(hot_roots)

        # ---- findings ----
        for fn in graph.functions.values():
            ft = taints.get(fn.key)
            if ft is None:
                continue
            is_root = fn.key in lap_fns
            if not is_root and fn.key not in hot:
                continue
            laps = lap_fns.get(fn.key, [])
            for ev in ft.events:
                if DEVICE not in ev.origins:
                    continue  # propagates via summaries, reported upward
                if is_root:
                    phase = _phase_at(laps, ev.node.lineno)
                    if phase is None or phase in DELIVERY_PHASES:
                        continue
                    if ev.through is None and \
                            fn.ctx.relpath.startswith("doorman_tpu/solver/"):
                        # host-sync-in-hot-path's territory.
                        continue
                if ev.through is not None:
                    qn, rel = ev.through
                    emit(fn.ctx, ev.node,
                         f"passes a device-origin value into {qn}() "
                         f"({rel}), which host-syncs it via {ev.reason}: "
                         "the sync is reachable from a hot tick phase — "
                         "sync in delivery, or hand the helper host data",
                         )
                else:
                    emit(fn.ctx, ev.node,
                         f"{ev.reason} on a device-origin value in "
                         f"{fn.qualname} (reachable from a hot tick "
                         "phase): implicit host sync outside delivery — "
                         "keep hot-phase helpers async against the "
                         "device",
                         )

        # ---- donation safety (lexical, per file) ----
        for ctx in repo.files:
            for f in self._donation_findings(ctx):
                findings.setdefault(ctx.relpath, []).append(f)
        return findings

    # -- donation ------------------------------------------------------

    def _donation_findings(self, ctx: FileContext) -> List[Finding]:
        donors = self._donating_callables(ctx)
        if not donors:
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_donation(ctx, node, donors))
        return out

    @staticmethod
    def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                pos = []
                for elt in v.elts:
                    if not (isinstance(elt, ast.Constant)
                            and isinstance(elt.value, int)):
                        return None  # computed: cannot know, stay quiet
                    pos.append(elt.value)
                return tuple(pos)
            return None
        return None

    def _donating_callables(self, ctx: FileContext) -> Dict[str, Tuple[int, ...]]:
        """Local names bound to a jit with literal donate_argnums:
        decorated defs and `x = jax.jit(f, donate_argnums=...)`."""
        donors: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    # Both spellings: @jax.jit(donate_argnums=...) and
                    # @partial(jax.jit, donate_argnums=...).
                    if isinstance(dec, ast.Call) and "jit" in ast.unparse(dec):
                        pos = self._donated_positions(dec)
                        if pos:
                            donors[node.name] = pos
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                call = node.value
                if "jit" not in ast.unparse(call):
                    continue
                pos = self._donated_positions(call)
                if not pos:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        donors[tgt.id] = pos
                    elif isinstance(tgt, ast.Attribute) and isinstance(
                            tgt.value, ast.Name) and tgt.value.id == "self":
                        donors[tgt.attr] = pos
        return donors

    def _check_donation(self, ctx: FileContext, func: ast.AST,
                        donors: Dict[str, Tuple[int, ...]]) -> List[Finding]:
        out: List[Finding] = []
        dead: Dict[str, str] = {}  # name -> donating callee text

        def callee_key(call: ast.Call) -> Optional[str]:
            f = call.func
            if isinstance(f, ast.Name) and f.id in donors:
                return f.id
            if isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name) and f.value.id == "self" and \
                    f.attr in donors:
                return f.attr
            return None

        def read(node: ast.expr) -> None:
            # Statement granularity: reads in THIS expression happen
            # before its own donating call completes (args evaluate
            # first), so flag against the dead set as it stood, and
            # only then retire the newly donated names.
            newly_dead: Dict[str, str] = {}
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in dead:
                    out.append(self.finding(
                        ctx, n,
                        f"{n.id} was donated to {dead[n.id]}() "
                        "(donate_argnums) and is referenced afterwards: "
                        "a donated buffer is freed by XLA at the call — "
                        "rebind the result (`x = f(x)`) or drop the "
                        "donation",
                    ))
                    del dead[n.id]  # one report per donation
                elif isinstance(n, ast.Call):
                    key = callee_key(n)
                    if key is not None:
                        for i in donors[key]:
                            if i < len(n.args) and isinstance(
                                    n.args[i], ast.Name):
                                newly_dead[n.args[i].id] = key
            dead.update(newly_dead)

        def bind(tgt: ast.AST) -> None:
            if isinstance(tgt, ast.Name):
                dead.pop(tgt.id, None)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for e in tgt.elts:
                    bind(e)
            elif isinstance(tgt, ast.Starred):
                bind(tgt.value)

        def exec_stmt(stmt: ast.AST) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                if stmt.value is not None:
                    read(stmt.value)
                tgts = (stmt.targets if isinstance(stmt, ast.Assign)
                        else [stmt.target])
                for t in tgts:
                    bind(t)
                return
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    read(child)
                elif isinstance(child, ast.stmt):
                    exec_stmt(child)

        for stmt in func.body:
            exec_stmt(stmt)
        return out
