"""The doormanlint rule set. Each module holds one checker; ALL_CHECKERS
is the registry the CLI and `run_lint` resolve by default.

The first six are per-file (one ast at a time); the last three are the
v2 whole-program rules built on tools/lint/graph.py + dataflow.py
(lock-order, device-sync-taint, registry-coherence). seeded-determinism
straddles: its checks are per-file but its scope is the import-graph
derivation."""

from tools.lint.checkers.determinism import SeededDeterminism
from tools.lint.checkers.device_taint import DeviceSyncTaint
from tools.lint.checkers.fused_writer import FusedWriterDiscipline
from tools.lint.checkers.host_sync import HostSyncInHotPath
from tools.lint.checkers.jit_capture import JitClosureCapture
from tools.lint.checkers.lock_order import LockOrder
from tools.lint.checkers.locks import LockDiscipline
from tools.lint.checkers.phase_hygiene import TracePhaseHygiene
from tools.lint.checkers.registries import RegistryCoherence

ALL_CHECKERS = (
    JitClosureCapture,
    HostSyncInHotPath,
    FusedWriterDiscipline,
    SeededDeterminism,
    LockDiscipline,
    TracePhaseHygiene,
    LockOrder,
    DeviceSyncTaint,
    RegistryCoherence,
)

__all__ = [
    "ALL_CHECKERS",
    "JitClosureCapture",
    "HostSyncInHotPath",
    "FusedWriterDiscipline",
    "SeededDeterminism",
    "LockDiscipline",
    "TracePhaseHygiene",
    "LockOrder",
    "DeviceSyncTaint",
    "RegistryCoherence",
]
