"""The doormanlint rule set. Each module holds one checker; ALL_CHECKERS
is the registry the CLI and `run_lint` resolve by default."""

from tools.lint.checkers.determinism import SeededDeterminism
from tools.lint.checkers.fused_writer import FusedWriterDiscipline
from tools.lint.checkers.host_sync import HostSyncInHotPath
from tools.lint.checkers.jit_capture import JitClosureCapture
from tools.lint.checkers.locks import LockDiscipline
from tools.lint.checkers.phase_hygiene import TracePhaseHygiene

ALL_CHECKERS = (
    JitClosureCapture,
    HostSyncInHotPath,
    FusedWriterDiscipline,
    SeededDeterminism,
    LockDiscipline,
    TracePhaseHygiene,
)

__all__ = [
    "ALL_CHECKERS",
    "JitClosureCapture",
    "HostSyncInHotPath",
    "FusedWriterDiscipline",
    "SeededDeterminism",
    "LockDiscipline",
    "TracePhaseHygiene",
]
