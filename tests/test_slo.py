"""SLO engine + trajectory comparator: floor/p99/reconvergence specs
evaluated over synthetic streams, Prometheus-style histogram quantiles,
and delta-vs-previous-round math over fabricated BENCH_r*.json
artifacts (including the r05 lesson: diagnostics rows must never be
ingested as metrics)."""

import json

import tests.conftest  # noqa: F401

from doorman_tpu.obs import metrics as metrics_mod
from doorman_tpu.obs import slo


def _by_name(verdicts):
    return {v["slo"]: v for v in verdicts}


def test_sample_quantile_nearest_rank():
    assert slo.sample_quantile([], 0.5) is None
    assert slo.sample_quantile([7.0], 0.99) == 7.0
    values = list(range(1, 101))
    assert slo.sample_quantile(values, 0.5) in (50, 51)  # rank rounding
    assert slo.sample_quantile(values, 0.99) == 99


def test_histogram_quantile_interpolates():
    reg = metrics_mod.Registry()
    h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
    assert slo.histogram_quantile(h, 0.5) is None  # no samples
    for v in (0.05,) * 5 + (0.5,) * 90 + (5.0,) * 5:
        h.observe(v)
    q50 = slo.histogram_quantile(h, 0.5)
    assert 0.1 < q50 < 1.0  # the median lands inside the middle bucket
    # A rank past the last finite bucket reports that bucket's bound.
    h2 = reg.histogram("lat2", buckets=(0.1,))
    h2.observe(5.0)
    assert slo.histogram_quantile(h2, 0.99) == 0.1


def test_histogram_quantile_with_labels():
    reg = metrics_mod.Registry()
    h = reg.histogram("req", labels=("method",), buckets=(0.01, 0.1, 1.0))
    for _ in range(100):
        h.observe(0.05, "GetCapacity")
    assert slo.histogram_quantile(h, 0.99, ("GetCapacity",)) <= 0.1
    assert slo.histogram_quantile(h, 0.99, ("Release",)) is None


def test_ceiling_and_floor_specs_over_samples():
    specs = [
        slo.SloSpec("tick_p50_ms", "max", 100.0,
                    {"type": "samples", "stream": "tick_ms",
                     "quantile": 0.5}, unit="ms"),
        slo.SloSpec("goodput_qps", "min", 1000.0,
                    {"type": "scalar", "key": "goodput"}, unit="qps"),
        slo.SloSpec("missing", "max", 1.0,
                    {"type": "samples", "stream": "nope"}),
    ]
    verdicts = _by_name(slo.SloEngine(specs).evaluate(slo.SloInputs(
        samples={"tick_ms": [80.0] * 9 + [500.0]},
        scalars={"goodput": 900.0},
    )))
    assert verdicts["tick_p50_ms"]["status"] == "pass"
    assert verdicts["tick_p50_ms"]["observed"] == 80.0
    assert verdicts["tick_p50_ms"]["margin"] == 20.0
    assert verdicts["goodput_qps"]["status"] == "fail"
    assert verdicts["goodput_qps"]["margin"] == -100.0
    # A missing stream is loudly no_data, never silently dropped.
    assert verdicts["missing"]["status"] == "no_data"
    assert verdicts["missing"]["observed"] is None


def test_top_band_goodput_floor():
    spec = slo.top_band_goodput_spec(0.99)
    engine = slo.SloEngine([spec])

    # Clean top band while lower bands shed: pass, tallies embedded.
    v = engine.evaluate(slo.SloInputs(band_tallies={
        0: {"admitted": 2, "shed": 98, "fast_fail": 0},
        2: {"admitted": 50, "shed": 0, "fast_fail": 0},
    }))[0]
    assert v["status"] == "pass" and v["observed"] == 1.0
    assert v["detail"]["band"] == 2
    assert v["detail"]["per_band"]["0"]["shed"] == 98

    # Shed reaching the top band: fail.
    v = engine.evaluate(slo.SloInputs(band_tallies={
        0: {"admitted": 0, "shed": 10, "fast_fail": 0},
        2: {"admitted": 90, "shed": 10, "fast_fail": 0},
    }))[0]
    assert v["status"] == "fail" and v["observed"] == 0.9

    # No admission tallies at all: no_data.
    v = engine.evaluate(slo.SloInputs())[0]
    assert v["status"] == "no_data"


def test_reconvergence_spec():
    spec = slo.reconvergence_spec(8)
    ok = slo.SloEngine([spec]).evaluate(
        slo.SloInputs(scalars={"reconverge_ticks": 3.0})
    )[0]
    assert ok["status"] == "pass" and ok["margin"] == 5.0
    blown = slo.SloEngine([spec]).evaluate(
        slo.SloInputs(scalars={"reconverge_ticks": 9.0})
    )[0]
    assert blown["status"] == "fail"


def test_histogram_source_through_registry():
    reg = metrics_mod.Registry()
    h = reg.histogram(
        "doorman_server_requests_durations", labels=("method",),
        buckets=(0.005, 0.01, 0.05, 0.1),
    )
    for _ in range(200):
        h.observe(0.008, "GetCapacity")
    specs = [slo.SloSpec(
        "get_capacity_p99_ms", "max", 50.0,
        {"type": "histogram",
         "metric": "doorman_server_requests_durations",
         "labels": ("GetCapacity",), "quantile": 0.99, "scale": 1000.0},
        unit="ms",
    )]
    v = slo.SloEngine(specs).evaluate(slo.SloInputs(registry=reg))[0]
    assert v["status"] == "pass"
    assert v["observed"] <= 10.0  # ms-scaled
    assert v["detail"]["count"] == 200


def test_server_slos_cover_the_contract():
    names = {s.name for s in slo.server_slos()}
    assert {
        "tick_budget_p50_ms", "tick_budget_p99_ms",
        "get_capacity_p99_ms", "top_band_goodput",
        "restore_staleness_s",
    } <= names


def test_storm_slo_verdicts():
    off = {
        "goodput_qps": 1000.0,
        "p99_s_by_band": {0: 0.030, 1: 0.025, 2: 0.020},
    }
    on = {
        "goodput_qps": 800.0,
        "ok_by_band": {0: 100, 1: 300, 2: 400},
        "shed_by_band": {0: 200, 1: 50},
        "p99_s_by_band": {0: 0.020, 1: 0.018, 2: 0.015},
    }
    verdicts = _by_name(slo.storm_slo_verdicts(
        off, on, goodput_floor_ratio=0.7
    ))
    top = verdicts["server_rpc_storm:top_band_goodput"]
    assert top["status"] == "pass"
    assert top["detail"]["per_band"]["0"]["shed"] == 200
    assert verdicts["server_rpc_storm:goodput_floor"]["status"] == "pass"
    assert verdicts["server_rpc_storm:goodput_floor"]["target"] == 700.0
    for band in (0, 1, 2):
        v = verdicts[f"server_rpc_storm:p99_ms_band{band}"]
        assert v["status"] == "pass", v
    # Admission-on tail past the off tail (+headroom) on one band: fail.
    on_bad = dict(on)
    on_bad["p99_s_by_band"] = {0: 0.200, 1: 0.018, 2: 0.015}
    verdicts = _by_name(slo.storm_slo_verdicts(off, on_bad))
    assert verdicts["server_rpc_storm:p99_ms_band0"]["status"] == "fail"


def test_bench_verdict_applies_to_wall_ms_rows():
    v = slo.bench_verdict({"metric": "server_tick_wide_1res_1m_wall_ms",
                           "value": 80.0})
    assert v["status"] == "pass" and v["target"] == slo.TICK_BUDGET_MS
    assert slo.bench_verdict({"metric": "x_qps", "value": 5.0}) is None
    assert slo.bench_verdict({"metric": "y_wall_ms", "value": "n/a"}) is None


def test_tpu_tick_budget_is_a_standing_spec():
    """The <10 ms one-chip target (ROADMAP "Sub-10 ms TPU tick") is a
    standing SloSpec: accelerator rounds report pass/fail
    automatically, CPU-fallback rounds yield an HONEST no_data verdict
    (never a fail that would poison the trajectory deltas) while still
    recording the CPU number in the detail."""
    spec = slo.tpu_tick_budget_spec()
    assert spec.target == slo.TPU_TICK_BUDGET_MS == 10.0
    assert spec.kind == "max"

    v = slo.tpu_tick_verdict(7.5, cpu_fallback=False)
    assert v["status"] == "pass" and v["margin"] == 2.5

    v = slo.tpu_tick_verdict(12.0, cpu_fallback=False)
    assert v["status"] == "fail"

    v = slo.tpu_tick_verdict(44.0, cpu_fallback=True)
    assert v["status"] == "no_data"
    assert v["observed"] is None
    assert v["detail"]["cpu_p50_ms"] == 44.0


# ----------------------------------------------------------------------
# Trajectory comparator
# ----------------------------------------------------------------------


def _write_round(tmp_path, n, lines):
    tail = "\n".join(json.dumps(obj) for obj in lines)
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(
        json.dumps({"n": n, "cmd": "python bench.py", "rc": 0,
                    "tail": tail})
    )


def test_trajectory_uses_latest_round_and_skips_diagnostics(tmp_path):
    _write_round(tmp_path, 1, [
        {"metric": "tick_wall_ms", "value": 200.0, "unit": "ms",
         "p99_ms": 260.0},
    ])
    _write_round(tmp_path, 2, [
        {"metric": "tick_wall_ms", "value": 150.0, "unit": "ms",
         "p99_ms": 190.0},
        {"metric": "only_in_r02", "value": 7.0, "unit": "x"},
    ])
    # r03 degraded: a diagnostics-only round (the r05 trap) plus a
    # non-JSON noise line; neither may become a metric.
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "n": 3, "rc": 3,
        "tail": "backend probe failed\n" + json.dumps(
            {"metric": "backend_unreachable", "value": 0, "unit": "error"}
        ),
    }))
    comp = slo.TrajectoryComparator(str(tmp_path))
    n, row = comp.previous("tick_wall_ms")
    assert n == 2 and row["value"] == 150.0
    assert comp.previous("backend_unreachable") is None
    assert comp.previous("never_measured") is None

    delta = comp.delta({"metric": "tick_wall_ms", "value": 120.0,
                        "p99_ms": 150.0})
    assert delta["round"] == 2
    assert delta["value"] == {"prev": 150.0, "delta": -30.0, "ratio": 0.8}
    assert delta["p99_ms"]["delta"] == -40.0
    assert comp.delta({"metric": "never_measured", "value": 1.0}) is None


def test_trajectory_slo_delta_matches_embedded_verdicts(tmp_path):
    _write_round(tmp_path, 4, [
        {"metric": "storm_qps", "value": 900.0, "unit": "qps",
         "slo": [{"slo": "server_rpc_storm:top_band_goodput",
                  "status": "pass", "observed": 0.98}]},
        {"metric": "tick_wall_ms", "value": 150.0, "unit": "ms",
         "slo": {"slo": "tick_wall_ms:tick_budget", "status": "fail",
                 "observed": 150.0}},
    ])
    comp = slo.TrajectoryComparator(str(tmp_path))
    d = comp.slo_delta({"slo": "server_rpc_storm:top_band_goodput",
                        "observed": 1.0})
    assert d == {"round": 4, "prev_status": "pass",
                 "prev_observed": 0.98, "delta_observed": 0.02}
    # A dict-valued (single) verdict is matched too.
    d = comp.slo_delta({"slo": "tick_wall_ms:tick_budget",
                        "observed": 90.0})
    assert d["prev_status"] == "fail"
    assert comp.slo_delta({"slo": "unknown", "observed": 1.0}) is None


def test_trajectory_on_missing_dir_is_empty(tmp_path):
    comp = slo.TrajectoryComparator(str(tmp_path / "nope"))
    assert comp.rounds == []
    assert comp.delta({"metric": "x", "value": 1.0}) is None


def test_bench_cpu_fallback_tags_every_row(monkeypatch):
    """The r04/r05 fix: an engaged CPU fallback pins the backend env
    BEFORE any in-process jax use, lands a diagnostic (never a metric
    row), and tags every subsequently emitted metric row."""
    import os
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import bench

    # Sandbox bench's process-global state and env for this test.
    monkeypatch.setattr(bench, "_CPU_FALLBACK", "")
    monkeypatch.setattr(bench, "_DIAGNOSTICS", [])
    monkeypatch.setattr(bench, "_EMITTED", [])
    monkeypatch.setattr(bench, "write_artifact", lambda **kw: None)
    monkeypatch.setenv(
        "JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", "cpu")
    )
    if "XLA_FLAGS" in os.environ:
        monkeypatch.setenv("XLA_FLAGS", os.environ["XLA_FLAGS"])
    else:
        monkeypatch.delenv("XLA_FLAGS", raising=False)

    bench._engage_cpu_fallback("backend_unreachable", "probe timed out")
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count" in os.environ[
        "XLA_FLAGS"
    ]
    # The fallback itself is a diagnostic, never a metric row.
    assert bench._DIAGNOSTICS[-1]["diagnostic"] == "cpu_fallback"
    assert "metric" not in bench._DIAGNOSTICS[-1]

    row = {"metric": "server_tick_1m_leases_native_store_wall_ms",
           "value": 50.0, "unit": "ms"}
    bench._annotate_row(row)
    assert row["cpu_fallback"] == "backend_unreachable"
    assert row["slo"]["status"] == "pass"
    assert row["delta_vs_prev"] is None or "round" in row["delta_vs_prev"]
