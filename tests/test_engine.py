"""The tick-engine conformance suite + admission-fused staging parity.

One contract, four solver paths: `solver/engine.py` owns the stage
skeleton (staging -> solve -> delivery) and the shared chokepoints; the
single-device resident, mesh resident, wide (chunked), mesh-wide, and
BatchTickAdapter paths all implement the same dispatch/collect/step
surface. This suite pins the contract ACROSS the paths, so a
stage-contract change cannot drift just one of them (it subsumes the
parity overlap of the per-path suites, which keep their path-specific
scenarios):

  * conformance: the dispatch/collect surface (idempotent collect,
    tick counters, the engine phase vocabulary) and cross-path store
    parity against the BatchSolver ground truth over churn that mixes
    bf16-exact and non-exact wants — so the compact transfer encodings
    (engine.bf16_exact, engine.compact_index_dtype) are pinned
    byte-identical by the same run;
  * pipelining: PipelinedTicker depth semantics — deferred write-back
    converges to the same fixpoint, drop() is benign, foreign-solver
    handles are dropped not collected;
  * fused staging: byte-identity of the admission-fused staging path
    vs the store->drain->pack round trip, solver-level and server-level
    (native + python stores, mixed priority bands, has-carrying
    refreshes); a mid-window mastership flip falls back to the
    round-trip path cleanly;
  * loud out-of-range dirty rids: the row-LUT alias assert and the
    engine anomaly hook.
"""

import asyncio

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.core.resource import Resource
from doorman_tpu.parallel import make_mesh
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.solver.batch import BatchSolver
from doorman_tpu.solver.engine import (
    PHASES,
    BatchTickAdapter,
    PipelinedTicker,
    bf16_exact,
    compact_index_dtype,
)
from doorman_tpu.solver.resident import ResidentDenseSolver
from doorman_tpu.solver.resident_wide import WideResidentSolver
from tests.test_resident_solver import all_leases, make_world

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

# Wide float tolerance (two-level chunk reduction re-associates sums;
# see tests/test_resident_wide.py for the bound's derivation).
RTOL = 1e-9
ATOL = 1e-9

PATHS = ("batch", "resident", "resident_mesh", "wide", "wide_mesh")


def make_path(path, engine, clock):
    """One tick engine per path name, all over the same world shape."""
    if path == "batch":
        return BatchTickAdapter(BatchSolver(dtype=np.float64, clock=clock))
    mesh = make_mesh() if path.endswith("_mesh") else None
    if path.startswith("resident"):
        return ResidentDenseSolver(
            engine, dtype=np.float64, clock=clock, rotate_ticks=1,
            mesh=mesh,
        )
    # chunk width 8 over 9 clients/resource: every resource spans two
    # chunk rows (the straddling case the mesh-wide path must reduce
    # bit-stably).
    return WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8, mesh=mesh,
    )


def conformance_churn(resources, step, rng):
    """Shared mutation stream: wants churn (alternating bf16-exact
    small integers and non-bf16-exact fractions, so both compact-upload
    encodings are exercised and pinned), releases, and new clients."""
    res = resources[step % len(resources)]
    i = resources.index(res)
    wants = (
        float(rng.integers(1, 200))
        if step % 2 == 0
        # 1/3 does not round-trip bfloat16: forces the full-width
        # wants upload (bf16_exact False) on odd steps.
        else float(rng.integers(1, 200)) + 1.0 / 3.0
    )
    res.store.assign(
        f"c{i}_0", 60.0, 5.0, res.store.get(f"c{i}_0").has, wants, 1
    )
    if step % 3 == 1:
        res2 = resources[(step * 7) % len(resources)]
        res2.store.release(f"c{resources.index(res2)}_1")
    if step % 3 == 2:
        res3 = resources[(step * 5) % len(resources)]
        res3.store.assign(
            f"new{step}_{resources.index(res3)}", 60.0, 5.0, 0.0,
            float(rng.integers(1, 50)), 2,
        )


def assert_store_parity(ref, got, path, msg=""):
    """Narrow paths are byte-identical to the BatchSolver; the wide
    paths carry the documented two-level reassociation tolerance."""
    assert ref.keys() == got.keys(), f"{path} membership diverged {msg}"
    for key in ref:
        if path.startswith("wide"):
            np.testing.assert_allclose(
                got[key], ref[key], rtol=RTOL, atol=ATOL,
                err_msg=f"{path} lease {key} {msg}",
            )
        else:
            assert got[key] == ref[key], (
                f"{path} lease {key} {msg}: {got[key]} != {ref[key]}"
            )


def test_conformance_store_parity_across_all_paths():
    """The load-bearing pin: one churn stream through every path, the
    BatchSolver world as ground truth, stores compared per tick."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    worlds = {p: make_world(clock) for p in PATHS}
    engines = {
        p: make_path(p, worlds[p][0], clock) for p in PATHS
    }
    rngs = {p: np.random.default_rng(99) for p in PATHS}
    for step in range(8):
        for p in PATHS:
            conformance_churn(worlds[p][1], step, rngs[p])
        if step == 4:
            # Learning-mode flip: the config epoch bump makes every
            # engine re-read templates mid-run.
            for p in PATHS:
                worlds[p][1][2].learning_mode_end = t[0] + 2.5
        epoch = 1 if step >= 4 else 0
        for p in PATHS:
            engines[p].step(worlds[p][1], epoch)
        ref = all_leases(worlds["batch"][1])
        for p in PATHS:
            if p == "batch":
                continue
            assert_store_parity(
                ref, all_leases(worlds[p][1]), p, f"step {step}"
            )
        t[0] += 1.0


@pytest.mark.parametrize("path", PATHS)
def test_dispatch_collect_contract(path):
    """The stage-skeleton contract every path honors: dispatch returns
    a collectible handle, collect is idempotent, counters move, and
    the phase vocabulary is the engine's (batch keeps its own
    pack/solve/apply subset)."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    eng = make_path(path, engine, clock)

    handle = eng.dispatch(resources, 0)
    assert eng.collect(handle) >= 0
    assert eng.collect(handle) == 0  # idempotent: nothing applies twice
    assert eng.ticks == 1
    assert eng.step(resources, 0) >= 0
    assert eng.ticks == 2
    assert eng.last_tick_seconds >= 0.0
    if isinstance(eng, BatchTickAdapter):
        assert {"pack", "solve", "apply"} <= set(eng.phase_s)
    else:
        assert set(PHASES) <= set(eng.phase_s)
        # The engine laps real phases (staging is host-side assembly,
        # split from the device window). Fused mode (the default) runs
        # the device window as ONE "fused" lap; round-trip mode keeps
        # the separate "upload" placement lap.
        assert eng.phase_s["staging"] > 0.0
        if eng.fused_tick:
            assert eng.phase_s["fused"] > 0.0
            assert eng.phase_s["upload"] == 0.0
        else:
            assert eng.phase_s["upload"] > 0.0


@pytest.mark.parametrize("path", ("resident", "wide"))
def test_idle_fast_path_conformance(path):
    """Quiet stores cost no device work on every resident path: after
    two full rotations with no changes, ticks are served idle — and
    any write resumes real ticks immediately."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    eng = make_path(path, engine, clock)
    for _ in range(12):
        eng.step(resources, 0)
        t[0] += 0.5
    assert eng.idle_ticks > 0
    idle_before = eng.idle_ticks
    resources[0].store.assign(
        "c0_0", 60.0, 5.0, resources[0].store.get("c0_0").has, 7.0, 1
    )
    eng.step(resources, 0)
    assert eng.idle_ticks == idle_before  # a write resumed real ticks


@pytest.mark.parametrize("path", ("resident", "wide"))
def test_pipelined_ticker_depth2_converges(path):
    """Depth-2 pipelining defers each tick's write-back one tick; once
    churn stops, the flushed store converges to the same fixpoint as
    the collect-before-dispatch reference. drop() mid-run is benign
    (uncollected grants re-deliver through rotation), and a foreign
    solver's handle is dropped, never collected."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    ref = make_path(path, eng_a, clock)
    piped = make_path(path, eng_b, clock)
    pipe = PipelinedTicker(depth=2)
    assert pipe.depth == 2

    rng_a, rng_b = (np.random.default_rng(7) for _ in range(2))
    for step in range(6):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        ref.step(res_a, 0)
        pipe.step(piped, res_b, 0)
        if step == 3:
            pipe.drop()  # a mastership flip would drop in-flight work
        t[0] += 1.0
    # Quiesce: no more churn; rotation re-delivers everything (rotate=1
    # here, so two quiet ticks cover the dropped tick's rows too).
    for _ in range(3):
        ref.step(res_a, 0)
        pipe.step(piped, res_b, 0)
        t[0] += 1.0
    assert pipe.flush(piped) > 0
    assert len(pipe) == 0
    assert_store_parity(
        all_leases(res_a), all_leases(res_b), path, "after flush"
    )
    # Foreign handles: a replacement solver's step drops the old
    # solver's in-flight handle instead of collecting it.
    stale = piped.dispatch(res_b, 0)
    pipe._queue.append((piped, stale))
    replacement = make_path(path, eng_b, clock)
    pipe.depth = 1
    pipe.step(replacement, res_b, 0)
    assert not stale.collected
    pipe.flush()


# ----------------------------------------------------------------------
# Admission-fused staging parity
# ----------------------------------------------------------------------


def fused_churn(resources, res_rids, solver, step, rng):
    """The churn stream replayed as admission windows: write the store,
    then stage the touched rows (exactly what the coalescer's grouped
    pass does through server._fused_stage). Mixed has-carrying
    refreshes and releases ride along."""
    touched = set()
    res = resources[step % len(resources)]
    i = resources.index(res)
    res.store.assign(
        f"c{i}_0", 60.0, 5.0, res.store.get(f"c{i}_0").has,
        float(rng.integers(1, 200)), 1,
    )
    touched.add(i)
    if step % 2 == 1:
        res2 = resources[(step * 3) % len(resources)]
        i2 = resources.index(res2)
        res2.store.assign(
            f"c{i2}_2", 60.0, 5.0, res2.store.get(f"c{i2}_2").has,
            float(rng.integers(1, 100)), 1,
        )
        touched.add(i2)
    if solver is not None:
        solver.stage_rids(res_rids[sorted(touched)])
    return touched


def test_fused_staging_solver_parity():
    """Byte-identity of the fused staging path vs the round-trip pack
    at the solver level, with an untracked-write invalidation in the
    middle (the stale entry must NOT ship)."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    plain = ResidentDenseSolver(
        eng_a, dtype=np.float64, clock=clock, rotate_ticks=1
    )
    fused = ResidentDenseSolver(
        eng_b, dtype=np.float64, clock=clock, rotate_ticks=1
    )
    staging = fused.attach_staging()
    assert fused.attach_staging() is staging  # idempotent
    rids_a = np.array([r.store._rid for r in res_a], np.int32)
    rids_b = np.array([r.store._rid for r in res_b], np.int32)

    rng_a, rng_b = (np.random.default_rng(21) for _ in range(2))
    fused_hits = 0
    for step in range(10):
        fused_churn(res_a, rids_a, None, step, rng_a)
        touched = fused_churn(res_b, rids_b, fused, step, rng_b)
        if step == 5:
            # An untracked writer (e.g. a release path) touches a row
            # AFTER the window staged it: without invalidation the
            # fused tick would ship the stale pack and the write's
            # consumed dirty flag would lose it.
            i = sorted(touched)[0]
            for world, rid_arr, solver in (
                (res_a, rids_a, None), (res_b, rids_b, fused),
            ):
                world[i].store.assign(
                    f"c{i}_3", 60.0, 5.0,
                    world[i].store.get(f"c{i}_3").has, 123.0, 1,
                )
                if solver is not None:
                    solver.staging.invalidate(int(rid_arr[i]))
        plain.step(res_a, 0)
        fused.step(res_b, 0)
        fused_hits += fused.last_fused["rows"]
        assert_store_parity(
            all_leases(res_a), all_leases(res_b), "resident",
            f"fused step {step}",
        )
        t[0] += 1.0
    assert fused_hits > 0  # the cache actually served rows
    st = staging.status()
    assert st["windows_total"] >= 9 and st["staged_rows_total"] > 0


def test_fused_staging_wholesale_invalidate_on_sweep():
    """An expiry sweep that removes anything invalidates the whole
    cache (the sweep does not say which rows): the next tick falls
    back to the round-trip pack and stays byte-identical."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    plain = ResidentDenseSolver(
        eng_a, dtype=np.float64, clock=clock, rotate_ticks=1
    )
    fused = ResidentDenseSolver(
        eng_b, dtype=np.float64, clock=clock, rotate_ticks=1
    )
    fused.attach_staging()
    rids_b = np.array([r.store._rid for r in res_b], np.int32)
    plain.step(res_a, 0)
    fused.step(res_b, 0)
    # Short-lease clients expire over the jump; the sweep's clean_all
    # removes them and must clear the staged pack below.
    for world in (res_a, res_b):
        world[0].store.assign("moth", 2.0, 1.0, 0.0, 9.0, 1)
    fused.stage_rids(rids_b[:1])
    t[0] += 30.0  # "moth" expires
    plain.step(res_a, 0)
    fused.step(res_b, 0)
    assert fused.last_fused["rows"] == 0  # cache was dropped, not used
    assert_store_parity(
        all_leases(res_a), all_leases(res_b), "resident", "post sweep"
    )


def test_out_of_range_dirty_rid_is_loud_when_aliasing():
    """The satellite pin: an out-of-range dirty rid must resolve to
    "not ours" through the reserved -1 slot — silently aliasing it onto
    a live row (the old `lut[np.minimum(...)]` behavior) corrupts that
    row's upload. Benign case: rids registered after the rebuild drain
    away quietly. Corrupt case: a reserved slot pointing at a real row
    raises AND fires the anomaly hook."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    solver = ResidentDenseSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1
    )
    solver.step(resources, 0)

    # Benign: a resource created after the rebuild dirties a rid above
    # the LUT; the tick ignores it (it is not in this solver's table).
    tpl = pb.ResourceTemplate(
        identifier_glob="late", capacity=10.0,
        algorithm=pb.Algorithm(
            kind=pb.Algorithm.PROPORTIONAL_SHARE,
            lease_length=60, refresh_interval=5,
        ),
    )
    late = Resource("late", tpl, clock=clock, store_factory=engine.store)
    late.store.assign("lc", 60.0, 5.0, 0.0, 5.0, 1)
    solver.step(resources, 0)  # no raise; the late rid drains to -1

    # Corrupt: the reserved trailing slot aliases row 0. A rid CLAMPED
    # onto it (strictly past the LUT — `late2` is one rid beyond
    # `late`, which sat exactly on the reserved index) must refuse to
    # scatter another resource's writes into row 0 — loud assert plus
    # an anomaly instant for the flight recorder.
    tpl2 = pb.ResourceTemplate(
        identifier_glob="later", capacity=10.0,
        algorithm=pb.Algorithm(
            kind=pb.Algorithm.PROPORTIONAL_SHARE,
            lease_length=60, refresh_interval=5,
        ),
    )
    late2 = Resource("later", tpl2, clock=clock, store_factory=engine.store)
    events = []
    solver.on_anomaly = lambda kind, detail: events.append((kind, detail))
    solver._row_lut[-1] = 0
    late2.store.assign("lc2", 60.0, 5.0, 0.0, 7.0, 1)
    with pytest.raises(AssertionError, match="alias"):
        solver.dispatch(resources, 0)
    assert events and events[0][0] == "dirty_rid_alias"
    assert events[0][1]["aliased_rows"] == [0]


# ----------------------------------------------------------------------
# Server-level fused parity (the coalescer as the tracked write path)
# ----------------------------------------------------------------------

SERVER_CONFIG = """
resources:
- identifier_glob: "fair*"
  capacity: 300
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
"""


async def _make_batch_server(fuse, native_store, clock):
    from doorman_tpu.admission import Admission
    from doorman_tpu.server.config import parse_yaml_config
    from doorman_tpu.server.election import TrivialElection
    from doorman_tpu.server.server import CapacityServer

    server = CapacityServer(
        f"eng-{'fused' if fuse else 'plain'}",
        TrivialElection(),
        mode="batch", tick_interval=60.0,  # ticks driven manually
        minimum_refresh_interval=0.0,
        native_store=native_store,
        clock=clock,
        admission=Admission(coalesce_window=0.05),
        fuse_admission=fuse,
        flightrec_capacity=64,
    )
    await server.start(0, host="127.0.0.1")
    await server.load_config(parse_yaml_config(SERVER_CONFIG))
    await asyncio.sleep(0)
    return server


def _server_requests(round_index, prev=None):
    """Mixed bands over both resources; later rounds carry each path's
    own grants as `has` (a refreshing population)."""
    reqs = []
    for i in range(6):
        cid = f"cl{i}"
        req = pb.GetCapacityRequest(client_id=cid)
        for rid in (["fair0"] if i % 2 else ["fair0", "prop"]):
            rr = req.resource.add()
            rr.resource_id = rid
            rr.wants = 10.0 * (i + 1) + round_index
            rr.priority = i % 3
            if prev is not None:
                for resp in prev[cid].response:
                    if resp.resource_id == rid:
                        rr.has.CopyFrom(resp.gets)
        reqs.append(req)
    return reqs


async def _drive_window(server, reqs):
    tasks = [
        asyncio.create_task(server.GetCapacity(req, None)) for req in reqs
    ]
    outs = await asyncio.gather(*tasks)
    return {req.client_id: out for req, out in zip(reqs, outs)}


def _store_rows(server):
    return {
        rid: sorted(res.store.dump_rows())
        for rid, res in server.resources.items()
    }


@pytest.mark.parametrize("native_store", [False, True],
                         ids=["python-store", "native-store"])
def test_fused_server_parity(native_store):
    """End to end: the fused admission->engine staging path must be
    byte-identical (responses AND stores) to the round-trip path, over
    coalesced windows with mixed bands and has-carrying refreshes and
    manual batch ticks between rounds. On the Python store the fuse
    flag must be a clean no-op (no resident path exists to fuse)."""

    async def body():
        class Clock:
            t = 1_000.0

            def __call__(self):
                return self.t

        clock = Clock()
        plain = await _make_batch_server(False, native_store, clock)
        fused = await _make_batch_server(True, native_store, clock)
        try:
            prev_p = await _drive_window(plain, _server_requests(0))
            prev_f = await _drive_window(fused, _server_requests(0))
            for rnd in range(1, 4):
                await plain.tick_once()
                await fused.tick_once()
                clock.t += 1.0
                prev_p = await _drive_window(
                    plain, _server_requests(rnd, prev_p)
                )
                prev_f = await _drive_window(
                    fused, _server_requests(rnd, prev_f)
                )
                assert {
                    c: r.SerializeToString() for c, r in prev_p.items()
                } == {
                    c: r.SerializeToString() for c, r in prev_f.items()
                }, f"responses diverged in round {rnd}"
                assert _store_rows(plain) == _store_rows(fused), (
                    f"stores diverged in round {rnd}"
                )
            if native_store:
                st = fused._resident.staging.status()
                assert st["windows_total"] > 0  # fusion actually ran
                assert plain._resident.staging is None
            else:
                assert fused._resident is None  # nothing to fuse
        finally:
            await plain.stop()
            await fused.stop()

    asyncio.run(body())


def test_fused_mid_window_mastership_flip_falls_back():
    """A mastership flip mid-window: parked requests get redirects, the
    resident solver (and its staging cache) is dropped with the flip,
    and the next mastership serves through a clean round-trip rebuild."""

    async def body():
        class Clock:
            t = 1_000.0

            def __call__(self):
                return self.t

        clock = Clock()
        server = await _make_batch_server(True, True, clock)
        try:
            prev = await _drive_window(server, _server_requests(0))
            await server.tick_once()
            solver = server._resident
            assert solver is not None and solver.staging is not None

            # Requests park; the flip lands before the window flushes.
            tasks = [
                asyncio.create_task(server.GetCapacity(req, None))
                for req in _server_requests(1, prev)
            ]
            await asyncio.sleep(0)
            await server._on_is_master(False)
            outs = await asyncio.gather(*tasks)
            assert all(not out.response for out in outs)  # redirects
            assert server._resident is None  # solver dropped with flip
            assert len(server._resident_pipe) == 0

            # Back to master: a fresh solver, a fresh (empty) cache,
            # ticks run clean through the round-trip rebuild.
            await server._on_is_master(True)
            await _drive_window(server, _server_requests(2))
            await server.tick_once()
            await server.tick_once()  # collects the first tick's handle
            assert server._resident is not None
            assert server._resident.ticks >= 1
            assert server._resident.staging.status()["pending_rows"] == 0
        finally:
            await server.stop()

    asyncio.run(body())


# ----------------------------------------------------------------------
# Compact transfer encodings
# ----------------------------------------------------------------------


def test_bf16_exact_predicate():
    # Small integers round-trip bfloat16 exactly; 1/3 and large odd
    # integers do not; empty blocks never qualify.
    assert bf16_exact(np.arange(256, dtype=np.float64))
    assert not bf16_exact(np.array([1.0 / 3.0]))
    assert not bf16_exact(np.array([257.0]))  # needs 9 mantissa bits
    assert not bf16_exact(np.zeros(0))


def test_compact_index_dtype():
    assert compact_index_dtype(2**20) == np.int32
    assert compact_index_dtype(2**31) == np.int64
