"""Backoff and flagenv tests (parity with reference timeutil/flagenv
tests)."""

import argparse

from doorman_tpu.utils.backoff import backoff
from doorman_tpu.utils.flagenv import flag_to_env, populate


def test_backoff_growth_and_clamp():
    assert backoff(1.0, 60.0, 0) == 1.0
    assert backoff(1.0, 60.0, 1) == 1.3
    assert abs(backoff(1.0, 60.0, 2) - 1.69) < 1e-9
    assert backoff(1.0, 60.0, 1000) == 60.0


def test_flag_to_env():
    assert flag_to_env("DOORMAN", "config") == "DOORMAN_CONFIG"
    assert flag_to_env("DOORMAN", "debug-port") == "DOORMAN_DEBUG_PORT"


def test_populate_from_env(monkeypatch):
    monkeypatch.setenv("DOORMAN_PORT", "1234")
    monkeypatch.setenv("DOORMAN_CONFIG", "file:/tmp/x.yml")
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--config", default="")
    parser.add_argument("--other", default="unchanged")
    populate(parser, "DOORMAN")
    args = parser.parse_args([])
    assert args.port == 1234
    assert args.config == "file:/tmp/x.yml"
    assert args.other == "unchanged"


def test_store_true_and_false_from_env(monkeypatch):
    monkeypatch.setenv("DOORMAN_VERBOSE", "true")
    monkeypatch.setenv("DOORMAN_NO_COLOR", "true")
    parser = argparse.ArgumentParser()
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--no-color", action="store_false", dest="color")
    populate(parser, "DOORMAN")
    args = parser.parse_args([])
    assert args.verbose is True
    assert args.color is False  # env var applies the store_false flag


def test_command_line_beats_env(monkeypatch):
    monkeypatch.setenv("DOORMAN_PORT", "1234")
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    populate(parser, "DOORMAN")
    args = parser.parse_args(["--port", "7"])
    assert args.port == 7


def test_probe_backend_returns_devices_when_backend_is_up():
    """The watchdog's happy path: under the test conftest (CPU pinned)
    the backend comes up immediately and the probe reports devices with
    no error; the timeout/error paths are exercised by bench.py and
    __graft_entry__ against a genuinely unreachable backend."""
    from doorman_tpu.utils.backend import probe_backend

    devices, exc = probe_backend(timeout_s=60.0)
    assert exc is None
    assert devices  # the 8 virtual CPU devices


def test_probe_backend_or_reason_happy_and_failure_messages():
    """The shared diagnostic formatting the bench and entry point both
    use: devices on success, a reason string naming the failure mode
    otherwise."""
    from doorman_tpu.utils import backend

    devices, reason, exc = backend.probe_backend_or_reason(timeout_s=60.0)
    assert devices and reason is None and exc is None

    # Failure paths, via the underlying probe's two shapes.
    orig = backend.probe_backend
    try:
        boom = ValueError("boom")
        backend.probe_backend = lambda t: (None, boom)
        _, reason, exc = backend.probe_backend_or_reason(5.0)
        assert reason == "ValueError: boom" and exc is boom
        backend.probe_backend = lambda t: (None, None)
        _, reason, exc = backend.probe_backend_or_reason(5.0)
        assert "did not initialize within 5s" in reason and exc is None
    finally:
        backend.probe_backend = orig


def test_split_for_download_thresholds():
    """Small or low-rank arrays pass through; big ones split into
    leading-axis views that cover the array exactly."""
    import numpy as np

    from doorman_tpu.utils.transfer import split_for_download

    small = np.zeros((8, 8), np.float32)
    assert split_for_download(small) == [small]
    assert len(split_for_download(np.float32(3.0))) == 1  # scalar path

    big = np.arange(2 * (1 << 17), dtype=np.float32).reshape(-1, 64)
    parts = split_for_download(big)
    assert len(parts) == 4  # ~256 KB per stream at 1 MB
    np.testing.assert_array_equal(np.concatenate(parts), big)

    from doorman_tpu.utils.transfer import land_parts

    np.testing.assert_array_equal(land_parts(parts), big)
