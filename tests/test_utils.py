"""Backoff and flagenv tests (parity with reference timeutil/flagenv
tests)."""

import argparse

from doorman_tpu.utils.backoff import backoff
from doorman_tpu.utils.flagenv import flag_to_env, populate


def test_backoff_growth_and_clamp():
    assert backoff(1.0, 60.0, 0) == 1.0
    assert backoff(1.0, 60.0, 1) == 1.3
    assert abs(backoff(1.0, 60.0, 2) - 1.69) < 1e-9
    assert backoff(1.0, 60.0, 1000) == 60.0


def test_flag_to_env():
    assert flag_to_env("DOORMAN", "config") == "DOORMAN_CONFIG"
    assert flag_to_env("DOORMAN", "debug-port") == "DOORMAN_DEBUG_PORT"


def test_populate_from_env(monkeypatch):
    monkeypatch.setenv("DOORMAN_PORT", "1234")
    monkeypatch.setenv("DOORMAN_CONFIG", "file:/tmp/x.yml")
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--config", default="")
    parser.add_argument("--other", default="unchanged")
    populate(parser, "DOORMAN")
    args = parser.parse_args([])
    assert args.port == 1234
    assert args.config == "file:/tmp/x.yml"
    assert args.other == "unchanged"


def test_store_true_and_false_from_env(monkeypatch):
    monkeypatch.setenv("DOORMAN_VERBOSE", "true")
    monkeypatch.setenv("DOORMAN_NO_COLOR", "true")
    parser = argparse.ArgumentParser()
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--no-color", action="store_false", dest="color")
    populate(parser, "DOORMAN")
    args = parser.parse_args([])
    assert args.verbose is True
    assert args.color is False  # env var applies the store_false flag


def test_command_line_beats_env(monkeypatch):
    monkeypatch.setenv("DOORMAN_PORT", "1234")
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    populate(parser, "DOORMAN")
    args = parser.parse_args(["--port", "7"])
    assert args.port == 7


def test_wait_for_backend_retries_and_reports(monkeypatch):
    """The tunnel-blip waiter probes in throwaway subprocesses: it
    returns None as soon as one probe succeeds and the last failure
    reason when all attempts fail (loop logic only — a real spawn here
    would race the shared device tunnel's actual state)."""
    import subprocess
    import types

    from doorman_tpu.utils import backend

    calls = {"n": 0}

    def fake_run(args, **kw):
        calls["n"] += 1
        if calls["n"] < 3:
            return types.SimpleNamespace(
                returncode=1, stdout="", stderr="boom"
            )
        return types.SimpleNamespace(returncode=0, stdout="ok\n", stderr="")

    monkeypatch.setattr(subprocess, "run", fake_run)
    assert backend.wait_for_backend(attempts=5, per_timeout_s=0.05) is None
    assert calls["n"] == 3

    calls["n"] = 0

    def always_timeout(args, **kw):
        calls["n"] += 1
        raise subprocess.TimeoutExpired(cmd=args, timeout=1.0)

    monkeypatch.setattr(subprocess, "run", always_timeout)
    reason = backend.wait_for_backend(attempts=2, per_timeout_s=0.05)
    assert reason is not None and "did not initialize" in reason
    assert calls["n"] == 2

    # Unretryable environment breakage (no jax) reports immediately
    # instead of pacing through the whole retry schedule.
    calls["n"] = 0

    def broken_env(args, **kw):
        calls["n"] += 1
        return types.SimpleNamespace(
            returncode=1, stdout="",
            stderr="ModuleNotFoundError: No module named 'jax'",
        )

    monkeypatch.setattr(subprocess, "run", broken_env)
    reason = backend.wait_for_backend(attempts=5, per_timeout_s=0.05)
    assert "ModuleNotFoundError" in reason
    assert calls["n"] == 1
