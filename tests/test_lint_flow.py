"""doormanlint v2 (whole-program flow analysis): the graph substrate,
the three inter-procedural rules, the import-derived determinism scope,
and the operational gates.

Fixture style matches tests/test_lint.py: tiny source trees under
tmp_path with the repo-relative layout the checkers scope on. Every new
rule ships a known-bad fixture that produces EXACTLY the expected
finding and a known-good twin that stays clean (the acceptance
criterion), plus the real-repo assertions: federation/ is DERIVED as
chaos-reachable (the PR-10 near-miss this framework exists to close),
the full nine-rule suite runs clean, and the whole run stays inside
the wall-clock budget without ever importing jax.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import pytest

from tools.lint.core import RepoContext, load_files, run_lint

REPO_ROOT = Path(__file__).resolve().parents[1]


class Tree:
    def __init__(self, root: Path):
        self.root = root

    def write(self, rel: str, text: str) -> None:
        p = self.root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text, encoding="utf-8")

    def active(self, rules):
        return [
            f for f in run_lint(self.root, rules=rules) if not f.suppressed
        ]


@pytest.fixture()
def tree(tmp_path):
    return Tree(tmp_path)


# ---------------------------------------------------------------------
# the graph substrate
# ---------------------------------------------------------------------


def graph_of(tree):
    contexts, errors = load_files(tree.root)
    assert errors == []
    return RepoContext(tree.root, contexts).graph


def test_import_graph_includes_package_inits(tree):
    # Importing a.b executes a/__init__.py: the closure must include it
    # even though nothing names it directly.
    tree.write("doorman_tpu/chaos/run.py",
               "from doorman_tpu.lib.util import now\n")
    tree.write("doorman_tpu/lib/__init__.py", "")
    tree.write("doorman_tpu/lib/util.py", "def now():\n    return 0\n")
    g = graph_of(tree)
    reach = g.reachable_files(("doorman_tpu/chaos/",))
    assert "doorman_tpu/lib/util.py" in reach
    assert "doorman_tpu/lib/__init__.py" in reach


def test_relative_imports_resolve(tree):
    tree.write("doorman_tpu/chaos/__init__.py", "from . import helper\n")
    tree.write("doorman_tpu/chaos/helper.py", "x = 1\n")
    g = graph_of(tree)
    assert "doorman_tpu/chaos/helper.py" in \
        g.imports["doorman_tpu/chaos/__init__.py"]


def test_call_resolution_self_module_and_fallback(tree):
    tree.write("doorman_tpu/server/a.py", """
from doorman_tpu.server.b import helper


class A:
    def top(self, other):
        self.mine()          # self -> same class
        helper()             # imported symbol
        other.unique_leaf()  # unique-method fallback

    def mine(self):
        pass
""")
    tree.write("doorman_tpu/server/b.py", """
def helper():
    pass


class B:
    def unique_leaf(self):
        pass
""")
    g = graph_of(tree)
    top = g.function_at("doorman_tpu/server/a.py", "A.top")
    resolved = {t.qualname for _, targets in top.calls for t in targets}
    assert resolved == {"A.mine", "helper", "B.unique_leaf"}


def test_generic_method_names_stay_unresolved(tree):
    # `.get()` would weld every dict access to any repo class with a
    # get method; the fallback must refuse it.
    tree.write("doorman_tpu/server/a.py", """
class Cache:
    def get(self, k):
        return k


def use(d):
    return d.get(1)
""")
    g = graph_of(tree)
    use = g.function_at("doorman_tpu/server/a.py", "use")
    assert all(not targets for _, targets in use.calls)


# ---------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------

LOCK_A = """
import threading


class ASide:
    def __init__(self, b):
        self._lock = threading.Lock()
        self.b = b

    def push(self):
        with self._lock:
            self.b.pull_rows()

    def local_sweep(self):
        with self._lock:
            pass
"""

LOCK_B_CYCLE = """
import threading


class BSide:
    def __init__(self, a):
        self._lock = threading.Lock()
        self.a = a

    def pull_rows(self):
        with self._lock:
            pass

    def drain(self):
        with self._lock:
            self.a.local_sweep()
"""

LOCK_B_ORDERED = """
import threading


class BSide:
    def __init__(self, a):
        self._lock = threading.Lock()
        self.a = a

    def pull_rows(self):
        with self._lock:
            pass

    def drain(self):
        self.a.local_sweep()
        with self._lock:
            pass
"""


def test_lock_order_two_file_cycle(tree):
    # The PR-9/10 bug class: each file is locally consistent, the
    # deadlock only exists across the call graph.
    tree.write("doorman_tpu/server/a.py", LOCK_A)
    tree.write("doorman_tpu/server/b.py", LOCK_B_CYCLE)
    found = tree.active(rules=["lock-order"])
    assert len(found) == 1
    assert "lock-order cycle" in found[0].message
    assert "ASide._lock" in found[0].message
    assert "BSide._lock" in found[0].message


def test_lock_order_consistent_order_is_clean(tree):
    tree.write("doorman_tpu/server/a.py", LOCK_A)
    tree.write("doorman_tpu/server/b.py", LOCK_B_ORDERED)
    assert tree.active(rules=["lock-order"]) == []


BLOCKING_BAD = """
import queue
import threading


class Fanout:
    def __init__(self):
        self._lock = threading.Lock()
        self.outq = queue.Queue(maxsize=256)

    def publish(self, msg):
        with self._lock:
            self._send(msg)

    def _send(self, msg):
        self.outq.put(msg)
"""

BLOCKING_GOOD = """
import queue
import threading


class Fanout:
    def __init__(self):
        self._lock = threading.Lock()
        self.outq = queue.Queue(maxsize=256)
        self.seq = 0

    def publish(self, msg):
        with self._lock:
            self.seq += 1
        self._send(msg)

    def _send(self, msg):
        self.outq.put(msg)
"""


def test_lock_order_blocking_call_under_lock(tree):
    # A bounded queue.put two calls deep, reached with the lock held.
    tree.write("doorman_tpu/server/fanout.py", BLOCKING_BAD)
    found = tree.active(rules=["lock-order"])
    assert len(found) == 1
    assert "queue.put" in found[0].message
    assert "Fanout._lock" in found[0].message


def test_lock_order_narrowed_critical_section_is_clean(tree):
    tree.write("doorman_tpu/server/fanout.py", BLOCKING_GOOD)
    assert tree.active(rules=["lock-order"]) == []


def test_lock_order_lexical_sleep_under_lock(tree):
    tree.write("doorman_tpu/server/retry.py", """
import threading
import time


class Retry:
    def __init__(self):
        self._lock = threading.Lock()

    def spin(self):
        with self._lock:
            time.sleep(0.1)
""")
    found = tree.active(rules=["lock-order"])
    assert len(found) == 1
    assert "time.sleep" in found[0].message


def test_lock_order_holds_lock_annotation_feeds_edges(tree):
    # The annotated helper's acquisition happens "under" the caller's
    # lock even though no `with` is visible in either body alone.
    tree.write("doorman_tpu/server/ann.py", """
import threading


class Ann:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def fwd(self):  # holds-lock: self._a
        with self._b:
            pass

    def rev(self):
        with self._b:
            with self._a:
                pass
""")
    found = tree.active(rules=["lock-order"])
    assert len(found) == 1
    assert "cycle" in found[0].message


# ---------------------------------------------------------------------
# device-sync-taint
# ---------------------------------------------------------------------

TAINT_BAD = """
import jax.numpy as jnp


def _summarize(x):
    return float(x.sum())


class Engine:
    def dispatch(self, table, ph):
        gets = jnp.cumsum(table)
        total = _summarize(gets)
        ph.lap("solve")
        ph.lap("download")
        return total
"""

TAINT_GOOD = """
import jax.numpy as jnp


def _summarize(x):
    return float(x.sum())


class Engine:
    def dispatch(self, table, ph):
        gets = jnp.cumsum(table)
        ph.lap("solve")
        ph.lap("download")
        total = _summarize(gets)
        ph.lap("apply")
        return total
"""


def test_taint_sync_reached_through_helper(tree):
    # The upgrade over host-sync-in-hot-path: float() lives in a
    # helper, the phase only sees a call.
    tree.write("doorman_tpu/solver/fast.py", TAINT_BAD)
    found = tree.active(rules=["device-sync-taint"])
    assert len(found) == 1
    assert "_summarize" in found[0].message
    assert "float()" in found[0].message


def test_taint_delivery_phase_helper_is_clean(tree):
    tree.write("doorman_tpu/solver/fast.py", TAINT_GOOD)
    assert tree.active(rules=["device-sync-taint"]) == []


def test_taint_through_returning_helper(tree):
    # Device-ness survives a helper RETURN: the branch two hops away
    # from the jnp call is still a sync.
    tree.write("doorman_tpu/solver/deep.py", """
import jax.numpy as jnp


def _mask(table):
    return jnp.greater(table, 0)


def _any_row(table):
    m = _mask(table)
    if m.any():
        return 1
    return 0


class Engine:
    def dispatch(self, table, ph):
        n = _any_row(table)
        ph.lap("staging")
        return n
""")
    found = tree.active(rules=["device-sync-taint"])
    assert len(found) == 1
    assert "branching" in found[0].message


def test_taint_host_metadata_is_clean(tree):
    # .shape/.dtype are host attributes; branching on them is free.
    tree.write("doorman_tpu/solver/meta.py", """
import jax.numpy as jnp


def _rows(table):
    t = jnp.cumsum(table)
    if t.shape[0] > 8:
        return int(t.shape[0])
    return 8


class Engine:
    def dispatch(self, table, ph):
        n = _rows(table)
        ph.lap("staging")
        return n
""")
    assert tree.active(rules=["device-sync-taint"]) == []


def test_taint_donated_buffer_reused(tree):
    tree.write("doorman_tpu/solver/donate.py", """
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def step(table):
    return table + 1


def advance(table):
    out = step(table)
    return table.sum()
""")
    found = tree.active(rules=["device-sync-taint"])
    assert len(found) == 1
    assert "donated" in found[0].message


def test_taint_donation_rebind_is_clean(tree):
    tree.write("doorman_tpu/solver/donate.py", """
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def step(table):
    return table + 1


def advance(table):
    table = step(table)
    return table.sum()
""")
    assert tree.active(rules=["device-sync-taint"]) == []


# The shadow-audit trap (PR-17): an oracle comparison typed directly
# against the solve's device output inside a hot tick phase is a
# device->host sync on the hot path — exactly what obs/audit.py exists
# to avoid (it snapshots host copies and compares off-thread). The
# known-bad fixture types the compare where it must not live; the
# known-good twin hands the helper a host copy in the delivery segment.

AUDIT_BAD = """
import jax.numpy as jnp


def _audit_compare(gets, oracle):
    return bool((gets != oracle).any())


class Engine:
    def dispatch(self, table, oracle, ph):
        gets = jnp.cumsum(table)
        diverged = _audit_compare(gets, oracle)
        ph.lap("solve")
        ph.lap("download")
        return diverged
"""

AUDIT_GOOD = """
import jax.numpy as jnp
import numpy as np


def _audit_compare(gets, oracle):
    return bool((gets != oracle).any())


class Engine:
    def dispatch(self, table, oracle, ph):
        gets = jnp.cumsum(table)
        ph.lap("solve")
        ph.lap("download")
        host = np.asarray(gets)
        diverged = _audit_compare(host, oracle)
        ph.lap("apply")
        return diverged
"""


def test_taint_audit_compare_in_hot_phase(tree):
    tree.write("doorman_tpu/solver/audit_hot.py", AUDIT_BAD)
    found = tree.active(rules=["device-sync-taint"])
    assert len(found) == 1
    assert "_audit_compare" in found[0].message


def test_taint_audit_compare_in_delivery_is_clean(tree):
    tree.write("doorman_tpu/solver/audit_hot.py", AUDIT_GOOD)
    assert tree.active(rules=["device-sync-taint"]) == []


# ---------------------------------------------------------------------
# registry-coherence
# ---------------------------------------------------------------------


def test_stale_phase_entry(tree):
    # "warp" is budgeted by every consumer but no tick ever laps it.
    tree.write("doorman_tpu/solver/engine.py", """
PHASES = ("sweep", "solve", "warp")


def tick(ph):
    ph.lap("sweep")
    ph.lap("solve")
""")
    found = tree.active(rules=["registry-coherence"])
    assert len(found) == 1
    assert "'warp'" in found[0].message
    assert "never lapped" in found[0].message


def test_live_registries_are_clean(tree):
    tree.write("doorman_tpu/solver/engine.py", """
PHASES = ("sweep", "solve")


def tick(ph):
    ph.lap("sweep")
    ph.lap("solve")
""")
    tree.write("doorman_tpu/obs/trace.py", """
KNOWN_SPAN_NAMES = frozenset({"server.tick", "server.*"})
KNOWN_INSTANT_NAMES = frozenset({"shard.*"})
""")
    tree.write("doorman_tpu/server/handlers.py", """
def handle(tracer, method):
    with tracer.span("server.tick"):
        with tracer.span(f"server.{method}"):
            tracer.instant(f"shard.{method}")
""")
    assert tree.active(rules=["registry-coherence"]) == []


def test_stale_span_and_wildcard_entries(tree):
    tree.write("doorman_tpu/obs/trace.py", """
KNOWN_SPAN_NAMES = frozenset({"server.tick", "persist.snapshot"})
KNOWN_INSTANT_NAMES = frozenset({"federation.*"})
""")
    tree.write("doorman_tpu/server/handlers.py", """
def handle(tracer):
    with tracer.span("server.tick"):
        pass
""")
    found = tree.active(rules=["registry-coherence"])
    assert {m for f in found for m in [f.message]} and len(found) == 2
    messages = " | ".join(f.message for f in found)
    assert "persist.snapshot" in messages
    assert "federation.*" in messages


def test_ghost_tracked_writer_entry(tree):
    tree.write("doorman_tpu/server/server.py", """
FUSED_TRACKED_WRITERS = frozenset({"CapacityServer._decide"})


class CapacityServer:
    def _fused_invalidate(self):
        pass
""")
    found = tree.active(rules=["registry-coherence"])
    assert len(found) == 1
    assert "CapacityServer._decide" in found[0].message


def test_flightrec_read_without_producer(tree):
    tree.write("doorman_tpu/obs/flightrec.py", """
class FlightRecorder:
    def record(self, **fields):
        pass

    def overlay(self, records):
        out = []
        for rec in records:
            out.append(rec.get("phases"))
            out.append(rec.get("wall_ms"))
        return out
""")
    tree.write("doorman_tpu/server/server.py", """
class Server:
    def tick(self, fr, ms):
        rec = {}
        rec["wall_ms"] = ms
        fr.record(**rec)
""")
    found = tree.active(rules=["registry-coherence"])
    assert len(found) == 1
    assert "'phases'" in found[0].message


# ---------------------------------------------------------------------
# import-derived determinism scope
# ---------------------------------------------------------------------


def test_determinism_scope_follows_imports_not_prefixes(tree):
    # lib/ appears in no hand-kept list; it is covered the moment the
    # chaos runner can reach it.
    tree.write("doorman_tpu/lib/util.py", """
import time


def now():
    return time.time()
""")
    tree.write("doorman_tpu/chaos/runner.py",
               "from doorman_tpu.lib.util import now\n")
    found = tree.active(rules=["seeded-determinism"])
    assert len(found) == 1
    assert found[0].path == "doorman_tpu/lib/util.py"


def test_determinism_unreachable_module_is_exempt(tree):
    tree.write("doorman_tpu/lib/util.py", """
import time


def now():
    return time.time()
""")
    assert tree.active(rules=["seeded-determinism"]) == []


def test_federation_is_derived_chaos_reachable():
    # The PR-10 near-miss: the hand-kept list had to be extended for
    # federation/ by review. The derivation must cover every one of its
    # modules with no list to forget.
    contexts, errors = load_files(REPO_ROOT)
    assert errors == []
    repo = RepoContext(REPO_ROOT, contexts)
    reach = repo.graph.chaos_reachable()
    fed = [p for p in repo.by_path if p.startswith("doorman_tpu/federation/")]
    assert fed, "federation package disappeared?"
    missing = [p for p in fed if p not in reach]
    assert missing == []


def test_hand_kept_chaos_list_is_gone():
    from tools.lint.checkers import determinism

    assert not hasattr(determinism, "CHAOS_REACHABLE")


# ---------------------------------------------------------------------
# operational gates
# ---------------------------------------------------------------------


def test_real_repo_clean_under_all_nine_rules():
    from tools.lint.core import apply_baseline, load_baseline, default_checkers

    assert len(default_checkers()) == 9
    findings = run_lint(REPO_ROOT)
    apply_baseline(
        findings, load_baseline(REPO_ROOT / "tools" / "lint" / "baseline.json")
    )
    active = [f for f in findings if not f.suppressed and not f.baselined]
    assert active == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in active
    )


def test_wall_clock_budget_and_no_jax_import():
    # The lint job must stay a fast bare-CPU gate: the full nine-rule
    # run over the real repo in bounded CPU, without ever importing
    # jax (fresh interpreter so this suite's own imports don't
    # pollute). CPU time, not wall clock: the property is the work
    # lint does, and on a single-core box the rest of the suite
    # competing for the core would flake a wall-clock bound. Even CPU
    # time inflates ~2x when the box is oversubscribed (lower IPC per
    # on-CPU second), so the budget carries that headroom on top of
    # the ~8 s an idle run takes; it still catches an accidental
    # quadratic blowup, which is what the gate is for.
    code = (
        "import sys, time; t0 = time.process_time();\n"
        "from pathlib import Path;\n"
        "from tools.lint.core import run_lint;\n"
        f"fs = run_lint(Path({str(REPO_ROOT)!r}));\n"
        "elapsed = time.process_time() - t0;\n"
        "assert 'jax' not in sys.modules, 'lint imported jax';\n"
        "assert 'numpy' not in sys.modules, 'lint imported numpy';\n"
        "print(elapsed)\n"
    )
    res = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr
    elapsed = float(res.stdout.strip().splitlines()[-1])
    assert elapsed < 30.0, f"lint took {elapsed:.1f}s CPU (budget 30s)"


def test_changed_only_filters_reporting(tree, capsys):
    from tools.lint.cli import main

    tree.write("doorman_tpu/chaos/t.py", "import time\nx = time.time()\n")
    subprocess.run(["git", "init", "-q"], cwd=tree.root, check=True)
    # Nothing committed: the file is untracked, i.e. changed.
    rc = main(["--root", str(tree.root), "--rule", "seeded-determinism",
               "--changed-only", "--no-baseline"])
    assert rc == 1
    subprocess.run(["git", "add", "-A"], cwd=tree.root, check=True)
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "x"],
        cwd=tree.root, check=True,
    )
    # Committed and unchanged: same findings exist, none are reported.
    rc = main(["--root", str(tree.root), "--rule", "seeded-determinism",
               "--changed-only", "--no-baseline"])
    assert rc == 0
    capsys.readouterr()
