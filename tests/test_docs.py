"""Documentation examples stay executable: every YAML resource-repository
block in doc/*.md and README.md must load through the real config
parser — an example a user cannot paste verbatim is a doc bug (found
live: the capacity-group example shipped without the mandatory "*"
entry). A block demonstrating a REJECTED config opts out explicitly
with an `<!-- invalid -->` comment right before the fence."""

import re
from pathlib import Path

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.server.config import ConfigError, parse_yaml_config

_ROOT = Path(__file__).parent.parent
DOCS = sorted((_ROOT / "doc").glob("*.md")) + [_ROOT / "README.md"]


def yaml_blocks():
    for path in DOCS:
        text = path.read_text()
        for m in re.finditer(r"```ya?ml\n(.*?)```", text, re.S):
            block = m.group(1)
            if "resources" not in block:
                continue  # not a repository document (compose files etc.)
            # Deterministic opt-out: an example meant to be rejected
            # carries an explicit marker right before its fence.
            context = text[max(0, m.start() - 120):m.start()]
            expect_invalid = "<!-- invalid -->" in context
            yield pytest.param(
                block, expect_invalid,
                id=f"{path.name}:{text[:m.start()].count(chr(10)) + 1}",
            )


@pytest.mark.parametrize("block,expect_invalid", list(yaml_blocks()))
def test_doc_config_examples_load(block, expect_invalid):
    if expect_invalid:
        with pytest.raises(ConfigError):
            parse_yaml_config(block)
    else:
        parse_yaml_config(block)


def test_docs_have_config_examples():
    # The sweep must actually cover something; an accidental regex or
    # layout change silently skipping every block would pass vacuously.
    assert len(list(yaml_blocks())) >= 3


def test_deploy_manifests_parse_and_reference_real_entrypoints():
    """The deployment artifacts stay loadable and point at modules that
    actually exist: compose/k8s files rot silently otherwise (nothing
    else in CI reads them)."""
    import importlib

    import yaml

    deploy = _ROOT / "deploy"
    files = [deploy / "docker-compose.yml", deploy / "prometheus.yml"]
    files += sorted((deploy / "k8s").glob("*.yaml"))
    commands = set()
    for f in files:
        text = f.read_text()
        docs = [d for d in yaml.safe_load_all(text) if d]
        assert docs, f"{f} parsed to nothing"
        for m in re.finditer(r"doorman_tpu\.[a-z0-9_.]+", text):
            commands.add(m.group(0).rstrip("."))
    # The Dockerfile references entrypoints too (CMD, comments) and
    # nothing else in CI reads it.
    for m in re.finditer(
        r"doorman_tpu\.[a-z0-9_.]+", (deploy / "Dockerfile").read_text()
    ):
        commands.add(m.group(0).rstrip("."))
    # The server config shipped for the compose stack must validate.
    parse_yaml_config((deploy / "config.yml").read_text())
    assert commands, "no doorman_tpu entrypoints referenced in deploy/"
    for mod in sorted(commands):
        importlib.import_module(mod)
