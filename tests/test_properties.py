"""Property-based invariants of the allocation solvers (SURVEY §4: keep
the exact-grant tables as the oracle AND add property tests).

Invariants, for every algorithm lane and random demand table:
  * feasibility: sum(gets) <= capacity (except NO_ALGORITHM/learning,
    which grant wants/has by design);
  * no over-grant: gets <= wants (except learning: gets == has);
  * fair-share floor: a client wanting at least its weighted equal share
    receives at least that share when the resource is overloaded;
  * monotone group caps: tightening a group cap never increases usage.
"""

import numpy as np
import jax.numpy as jnp
import pytest

# Optional dep: a build without hypothesis skips the property suite
# instead of erroring the whole collection.
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

import tests.conftest  # noqa: F401

from doorman_tpu.algorithms.kinds import AlgoKind
from doorman_tpu.solver.dense import DenseBatch, solve_dense
from doorman_tpu.solver.priority import PriorityBatch, solve_priority

FEASIBLE_KINDS = (
    AlgoKind.PROPORTIONAL_SHARE,
    AlgoKind.FAIR_SHARE,
    AlgoKind.PROPORTIONAL_TOPUP,
    # The fairness portfolio: feasibility holds at ANY truncation of
    # their bounded fills (the level is monotone from below /
    # cap-peeling only ever un-claims), so these ride the general
    # invariants at full table sizes.
    AlgoKind.MAX_MIN_FAIR,
    AlgoKind.BALANCED_FAIRNESS,
    AlgoKind.PROPORTIONAL_FAIRNESS,
)


@st.composite
def demand_tables(draw, max_clients=24):
    n = draw(st.integers(1, max_clients))
    wants = draw(
        st.lists(
            st.floats(0, 1000, allow_nan=False), min_size=n, max_size=n
        )
    )
    has = draw(
        st.lists(st.floats(0, 500, allow_nan=False), min_size=n, max_size=n)
    )
    sub = draw(st.lists(st.integers(1, 5), min_size=n, max_size=n))
    capacity = draw(st.floats(1, 5000, allow_nan=False))
    return wants, has, sub, capacity


def dense_batch(wants, has, sub, capacity, kind, learning=False):
    n = len(wants)
    K = 32
    pad = lambda xs: np.pad(np.asarray(xs, np.float64), (0, K - n))
    active = np.zeros(K, bool)
    active[:n] = True
    return DenseBatch(
        wants=jnp.asarray(pad(wants))[None, :],
        has=jnp.asarray(pad(has))[None, :],
        subclients=jnp.asarray(pad(sub))[None, :],
        active=jnp.asarray(active)[None, :],
        capacity=jnp.asarray([capacity], jnp.float64),
        algo_kind=jnp.asarray([int(kind)], jnp.int32),
        learning=jnp.asarray([learning]),
        static_capacity=jnp.asarray([7.0], jnp.float64),
    )


@settings(max_examples=60, deadline=None)
@given(demand_tables(), st.sampled_from(FEASIBLE_KINDS))
def test_feasibility_and_no_overgrant(table, kind):
    wants, has, sub, capacity = table
    gets = np.asarray(
        solve_dense(dense_batch(wants, has, sub, capacity, kind))
    )[0]
    n = len(wants)
    assert gets[: n].sum() <= capacity * (1 + 1e-9) + 1e-6
    assert (gets[:n] <= np.asarray(wants) + 1e-9).all()
    assert (gets[:n] >= -1e-12).all()
    assert (gets[n:] == 0).all()


@settings(max_examples=40, deadline=None)
@given(demand_tables())
def test_learning_replays_has(table):
    wants, has, sub, capacity = table
    gets = np.asarray(
        solve_dense(
            dense_batch(
                wants, has, sub, capacity,
                AlgoKind.PROPORTIONAL_SHARE, learning=True,
            )
        )
    )[0]
    n = len(wants)
    np.testing.assert_allclose(gets[:n], np.asarray(has), rtol=1e-12)


@settings(max_examples=40, deadline=None)
@given(demand_tables())
def test_fair_share_floor(table):
    """In overload, a client wanting >= its weighted equal share gets at
    least that share (max-min fairness floor)."""
    wants, has, sub, capacity = table
    wants_arr = np.asarray(wants)
    sub_arr = np.asarray(sub, np.float64)
    if wants_arr.sum() <= capacity:
        return  # underloaded: everyone gets wants; floor is trivial
    gets = np.asarray(
        solve_dense(
            dense_batch(wants, has, sub, capacity, AlgoKind.FAIR_SHARE)
        )
    )[0][: len(wants)]
    equal = capacity / sub_arr.sum() * sub_arr
    demanding = wants_arr >= equal
    assert (gets[demanding] >= equal[demanding] * (1 - 1e-9) - 1e-6).all()


@settings(max_examples=40, deadline=None)
@given(demand_tables(max_clients=12))
def test_max_min_dominance(table):
    """MAX_MIN_FAIR is max-min fair at the client grain: in overload
    every unsatisfied client receives the common water level, and every
    satisfied client wants no more than it (so no grant can grow
    without shrinking a smaller one). max_clients=12 < FILL_ITERS keeps
    the bounded fill exactly converged (each non-final iteration
    saturates at least one client)."""
    wants, has, sub, capacity = table
    wants_arr = np.asarray(wants, np.float64)
    if wants_arr.sum() <= capacity:
        return  # underloaded: gets == wants, trivially max-min
    gets = np.asarray(
        solve_dense(
            dense_batch(wants, has, sub, capacity, AlgoKind.MAX_MIN_FAIR)
        )
    )[0][: len(wants)]
    unsat = gets < wants_arr * (1 - 1e-12) - 1e-12
    if unsat.any():
        level = gets[unsat].max()
        np.testing.assert_allclose(gets[unsat], level, rtol=1e-9)
        assert (wants_arr[~unsat] <= level * (1 + 1e-9) + 1e-6).all()
    # Subclient weights must NOT skew the fill (that is FAIR_SHARE):
    ones = [1] * len(wants)
    gets_unw = np.asarray(
        solve_dense(
            dense_batch(wants, has, ones, capacity, AlgoKind.MAX_MIN_FAIR)
        )
    )[0][: len(wants)]
    np.testing.assert_array_equal(gets, gets_unw)


@settings(max_examples=40, deadline=None)
@given(demand_tables(max_clients=12))
def test_proportional_fairness_pareto_and_oracle(table):
    """PROPORTIONAL_FAIRNESS is Pareto-efficient at convergence (the
    dual fixpoint exhausts min(capacity, Σwants) — no grant can grow
    without shrinking another) and matches its host reference."""
    from doorman_tpu.algorithms.tick import proportional_fairness_tick

    wants, has, sub, capacity = table
    wants_arr = np.asarray(wants, np.float64)
    gets = np.asarray(
        solve_dense(
            dense_batch(
                wants, has, sub, capacity, AlgoKind.PROPORTIONAL_FAIRNESS
            )
        )
    )[0][: len(wants)]
    ref = proportional_fairness_tick(
        capacity, wants_arr, np.asarray(sub, np.float64)
    )
    np.testing.assert_allclose(gets, ref, rtol=1e-9, atol=1e-9)
    target = min(capacity, float(wants_arr.sum()))
    assert gets.sum() >= target * (1 - 1e-9) - 1e-6  # Pareto: exhausted
    assert gets.sum() <= target * (1 + 1e-9) + 1e-6


@settings(max_examples=40, deadline=None)
@given(demand_tables(max_clients=12))
def test_balanced_fairness_oracle_and_feasible_slack(table):
    """BALANCED_FAIRNESS matches its host reference; unlike the
    efficient lanes it MAY leave capacity unclaimed (the insensitivity
    truncation), so only feasibility — not exhaustion — is pinned."""
    from doorman_tpu.algorithms.tick import balanced_fairness_tick

    wants, has, sub, capacity = table
    gets = np.asarray(
        solve_dense(
            dense_batch(
                wants, has, sub, capacity, AlgoKind.BALANCED_FAIRNESS
            )
        )
    )[0][: len(wants)]
    ref = balanced_fairness_tick(
        capacity, np.asarray(wants, np.float64),
        np.asarray(sub, np.float64),
    )
    np.testing.assert_allclose(gets, ref, rtol=1e-9, atol=1e-9)


@settings(max_examples=25, deadline=None)
@given(demand_tables(max_clients=12), st.floats(1, 2000))
def test_group_cap_monotone(table, cap2):
    """Tightening the group cap never increases the group's usage, and
    usage never exceeds the cap."""
    wants, has, sub, capacity = table
    n = len(wants)
    K = 16
    pad = lambda xs: np.pad(np.asarray(xs, np.float64), (0, K - n))
    active = np.zeros(K, bool)
    active[:n] = True

    def usage(group_cap):
        batch = PriorityBatch(
            wants=jnp.asarray(pad(wants))[None, :],
            weights=jnp.asarray(pad(sub))[None, :],
            band=jnp.zeros((1, K), jnp.int32),
            active=jnp.asarray(active)[None, :],
            capacity=jnp.asarray([capacity], jnp.float64),
            group=jnp.asarray([0], jnp.int32),
            group_cap=jnp.asarray([group_cap], jnp.float64),
        )
        return float(
            np.asarray(solve_priority(batch, num_bands=1)).sum()
        )

    lo_cap, hi_cap = sorted([cap2, cap2 * 2])
    u_lo, u_hi = usage(lo_cap), usage(hi_cap)
    assert u_lo <= lo_cap * (1 + 1e-9) + 1e-6
    assert u_hi <= hi_cap * (1 + 1e-9) + 1e-6
    assert u_lo <= u_hi * (1 + 1e-9) + 1e-6
