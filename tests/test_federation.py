"""Federated capacity tree conformance suite (doc/federation.md).

The pins:

  * router stability — the stable hash is a cross-process contract
    (pinned values), overrides and straddle routing behave;
  * discovery — jittered-TTL caching, invalidate-on-redirect, no
    re-resolution stampede;
  * PARITY — a federated deployment (N shards + the POP straddle
    reconciliation beat) converges to the single-root allocation over a
    churn schedule including straddling resources: bit-identical for
    NO_ALGORITHM / STATIC / PROPORTIONAL_SHARE (the final demand state
    makes the global scale factor dyadic, so the share quotient
    round-trips exactly — doc/federation.md derives when this holds),
    and within 1 ulp for FAIR_SHARE (the local water-fill re-derives
    the global level);
  * the capacity-sum invariant — Σ shard grants <= configured capacity
    on every tick, through a reconciler partition and heal, with the
    lost shard's slack re-offered only after its drain window;
  * per-shard warm takeover — a shard's candidates share a persist
    namespace; takeover restores exactly that shard's slice;
  * the aggregation adapter — device band sums match the store
    aggregation bit-for-bit and land through the engine phase streams;
  * the federated intermediate end to end — per-shard upstream fan-out
    over loopback gRPC, each root shard seeing only its own resources;
  * the shard_partition chaos plan — deterministic, blast radius
    contained (the generic invariant smoke in test_chaos_smoke.py runs
    it too; here the federation-specific arc is asserted).
"""

import asyncio
import math

import numpy as np
import pytest

import tests.conftest  # noqa: F401
import grpc

from doorman_tpu.algorithms import Request
from doorman_tpu.federation import (
    AggregationTickAdapter,
    FederatedClient,
    FederatedIntermediate,
    FederatedRoots,
    ShardDiscovery,
    ShardRouter,
    stable_shard,
)
from doorman_tpu.persist import MemoryBackend, PersistManager, parse_backend
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import CapacityStub
from doorman_tpu.server.election import TrivialElection, shard_lock_key
from doorman_tpu.server.server import CapacityServer


def run(coro):
    return asyncio.run(coro)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ----------------------------------------------------------------------
# Router
# ----------------------------------------------------------------------


def test_stable_shard_is_pinned_across_processes():
    # blake2b mod N: these values are a wire contract shared by every
    # client/intermediate in a deployment — a drift here would split
    # routing between versions, so the values themselves are pinned.
    assert stable_shard("res0", 2) == 1
    assert stable_shard("res1", 2) == 0
    assert stable_shard("res0", 4) == 1
    assert stable_shard("solo-a", 4) == 3
    # Same id, same answer, any number of calls.
    assert all(
        stable_shard("gamma", 8) == stable_shard("gamma", 8)
        for _ in range(10)
    )


def test_router_overrides_straddle_and_split():
    router = ShardRouter(
        4, overrides={"pinned": 2}, straddle=["shared"]
    )
    assert router.shard_of("pinned") == 2
    assert router.owners("pinned") == (2,)
    assert router.owners("shared") == (0, 1, 2, 3)
    assert router.is_straddling("shared")
    split = router.split(["res0", "res1", "pinned", "res0"])
    assert split[2] == ["pinned"]
    assert split[stable_shard("res0", 4)].count("res0") == 2
    with pytest.raises(ValueError):
        ShardRouter(4, overrides={"x": 7})
    with pytest.raises(ValueError):
        ShardRouter(0)


def test_shard_lock_key():
    assert shard_lock_key("/doorman/master", 3) == "/doorman/master/shard3"
    assert shard_lock_key("/doorman/master/", 0) == "/doorman/master/shard0"
    assert shard_lock_key("/doorman/master", -1) == "/doorman/master"


# ----------------------------------------------------------------------
# Discovery
# ----------------------------------------------------------------------


def test_discovery_cache_ttl_and_invalidate_on_redirect():
    clock = FakeClock()
    calls = []

    async def resolver(shard, seeds):
        calls.append(shard)
        return f"master{shard}:{len(calls)}"

    import random

    disc = ShardDiscovery(
        {0: "seed0", 1: ["seed1a", "seed1b"]},
        ttl=10.0, jitter=0.2, clock=clock,
        rng=random.Random(7), resolver=resolver,
    )

    async def body():
        # First hit resolves; repeats are served from cache — a fleet
        # refreshing every tick costs ONE Discovery per ttl, not one
        # per refresh.
        addr = await disc.master(0)
        for _ in range(50):
            assert await disc.master(0) == addr
        assert calls == [0]
        assert disc.hits == 50

        # The jittered deadline stays inside ttl*(1 ± jitter): fresh
        # before the lower bound...
        clock.advance(10.0 * 0.79)
        await disc.master(0)
        assert calls == [0]
        # ...and certainly re-resolved past the upper bound.
        clock.advance(10.0 * 0.42)
        await disc.master(0)
        assert calls == [0, 0]

        # Invalidate-on-redirect: a live connection observed the flip;
        # the cache takes the new master with NO Discovery round.
        disc.note_master(0, "flipped:1")
        assert await disc.master(0) == "flipped:1"
        assert calls == [0, 0]

        # invalidate() forces exactly that shard to re-resolve.
        disc.invalidate(0)
        await disc.master(1)
        await disc.master(0)
        assert calls == [0, 0, 1, 0]

    run(body())


# ----------------------------------------------------------------------
# Parity: federated == single root, over churn with straddling
# ----------------------------------------------------------------------

# One resource per algorithm lane; every "strad-*" resource straddles
# both shards (capacity split by the reconciler), the "solo-*"
# resources route whole. Wants schedules end in a demand state whose
# global proportional scale factor is DYADIC (W = 2*C), which makes the
# share quotient round-trip exact — the bit-identity precondition
# doc/federation.md derives.
PARITY_TEMPLATES = (
    ("strad-none", pb.Algorithm.NO_ALGORITHM, 100.0),
    ("strad-static", pb.Algorithm.STATIC, 7.0),
    ("strad-prop", pb.Algorithm.PROPORTIONAL_SHARE, 400.0),
    ("strad-fair", pb.Algorithm.FAIR_SHARE, 300.0),
    ("solo-a", pb.Algorithm.PROPORTIONAL_SHARE, 50.0),
    ("solo-b", pb.Algorithm.PROPORTIONAL_SHARE, 64.0),
)

# (resource, client, shard placement, wants per phase). Phases 1 and 2
# churn demand (including shard-local spikes that flip which shard is
# overloaded); phase 3 is the pinned end state.
PARITY_SCHEDULE = (
    ("strad-none", "n0", 0, (10.0, 35.0, 20.0)),
    ("strad-none", "n1", 1, (50.0, 5.0, 40.0)),
    ("strad-static", "t0", 0, (3.0, 11.0, 5.0)),
    ("strad-static", "t1", 1, (9.0, 2.0, 13.0)),
    ("strad-prop", "p0", 0, (100.0, 40.0, 100.0)),
    ("strad-prop", "p1", 0, (150.0, 90.0, 150.0)),
    ("strad-prop", "p2", 1, (250.0, 500.0, 250.0)),
    ("strad-prop", "p3", 1, (300.0, 70.0, 300.0)),
    ("strad-fair", "f0", 0, (50.0, 500.0, 50.0)),
    ("strad-fair", "f1", 0, (100.0, 20.0, 100.0)),
    ("strad-fair", "f2", 1, (150.0, 60.0, 150.0)),
    ("strad-fair", "f3", 1, (200.0, 10.0, 200.0)),
    ("solo-a", "sa", None, (30.0, 80.0, 45.0)),
    ("solo-b", "sb", None, (64.0, 10.0, 128.0)),
)

ROUNDS_PER_PHASE = 6


def _parity_repo():
    repo = pb.ResourceRepository()
    for rid, kind, capacity in PARITY_TEMPLATES:
        tpl = repo.resources.add()
        tpl.identifier_glob = rid
        tpl.capacity = capacity
        tpl.algorithm.kind = kind
        tpl.algorithm.lease_length = 600
        tpl.algorithm.refresh_interval = 1
        tpl.algorithm.learning_mode_duration = 0
    tpl = repo.resources.add()
    tpl.identifier_glob = "*"
    tpl.capacity = 1.0
    tpl.algorithm.kind = pb.Algorithm.PROPORTIONAL_SHARE
    tpl.algorithm.lease_length = 600
    tpl.algorithm.refresh_interval = 1
    tpl.algorithm.learning_mode_duration = 0
    return repo


async def _make_batch_server(name, clock, shard=None):
    server = CapacityServer(
        name, TrivialElection(), mode="batch",
        minimum_refresh_interval=0.0, clock=clock, shard=shard,
        flightrec_capacity=0,
    )
    await server.load_config(_parity_repo())
    await asyncio.sleep(0)
    return server


def test_sharded_vs_single_root_parity_over_churn():
    async def body():
        clock = FakeClock()
        router = ShardRouter(
            2,
            straddle=[r for r, *_ in PARITY_TEMPLATES if r.startswith("strad")],
        )
        root = await _make_batch_server("root", clock)
        shards = {
            0: await _make_batch_server("shard0", clock, shard=0),
            1: await _make_batch_server("shard1", clock, shard=1),
        }
        fed = FederatedRoots(router, shards, share_ttl=30.0, clock=clock)
        # Grants per deployment per (resource, client) — the `has` each
        # client reports back, exactly like a real refresh loop.
        has = {"root": {}, "fed": {}}
        try:
            # Bootstrap beat BEFORE the front door opens: installs the
            # even zero-demand split (C/N per shard) so no shard ever
            # serves a straddling resource against the full template
            # capacity (doc/federation.md, "Bringing up a federation").
            fed.reconcile_once()
            for phase in range(3):
                for _ in range(ROUNDS_PER_PHASE):
                    for rid, client, placement, wants in PARITY_SCHEDULE:
                        w = wants[phase]
                        lease, _ = root._decide(
                            rid,
                            Request(
                                client,
                                has["root"].get((rid, client), 0.0),
                                w,
                            ),
                        )
                        has["root"][(rid, client)] = lease.has
                        shard = (
                            placement
                            if placement is not None
                            else router.shard_of(rid)
                        )
                        lease, _ = shards[shard]._decide(
                            rid,
                            Request(
                                client,
                                has["fed"].get((rid, client), 0.0),
                                w,
                            ),
                        )
                        has["fed"][(rid, client)] = lease.has
                    await root.tick_once()
                    for server in shards.values():
                        await server.tick_once()
                    fed.reconcile_once()
                    clock.advance(1.0)
                    # The invariant rides every tick of the schedule:
                    # shard grants for a capacity-split resource never
                    # sum past the configured capacity.
                    for rid, kind, capacity in PARITY_TEMPLATES:
                        if kind not in (
                            pb.Algorithm.PROPORTIONAL_SHARE,
                            pb.Algorithm.FAIR_SHARE,
                        ) or not rid.startswith("strad"):
                            continue
                        total = sum(
                            s.resources[rid].store.sum_has
                            for s in shards.values()
                            if rid in s.resources
                        )
                        assert total <= capacity + 1e-6, (
                            phase, rid, total,
                        )

            # Convergence compare, per client.
            for rid, client, placement, wants in PARITY_SCHEDULE:
                shard = (
                    placement
                    if placement is not None
                    else router.shard_of(rid)
                )
                got_root = root.resources[rid].store.get(client).has
                got_fed = (
                    shards[shard].resources[rid].store.get(client).has
                )
                if rid == "strad-fair":
                    # The local water-fill re-derives the global level:
                    # 1 ulp of the grant scale.
                    assert (
                        abs(got_fed - got_root)
                        <= math.ulp(max(got_root, 1.0))
                    ), (rid, client, got_root, got_fed)
                else:
                    # Dyadic end state: bit-identical.
                    assert got_fed == got_root, (
                        rid, client, got_root, got_fed,
                    )
            # The pinned end state really was the interesting case:
            # proportional ran OVERLOADED (grants halved), not the
            # trivial wants-granted regime.
            assert has["root"][("strad-prop", "p0")] == 50.0
        finally:
            await root.stop()
            for server in shards.values():
                await server.stop()

    run(body())


# ----------------------------------------------------------------------
# Straddle reconciliation under partition (library level)
# ----------------------------------------------------------------------


def test_partition_freezes_share_then_decays_and_reoffers():
    async def body():
        clock = FakeClock()
        router = ShardRouter(2, straddle=["strad-prop"])
        shards = {
            0: await _make_batch_server("s0", clock, shard=0),
            1: await _make_batch_server("s1", clock, shard=1),
        }
        # Short drain window so the slack re-offer is observable: the
        # reconciler reads lease_length from the template (600s in the
        # parity repo) — override via the reconciler it builds.
        fed = FederatedRoots(router, shards, share_ttl=2.0, clock=clock)
        try:
            async def round_once(demands):
                for shard, client, w in demands:
                    shards[shard]._decide(
                        "strad-prop", Request(client, 0.0, w)
                    )
                for server in shards.values():
                    await server.tick_once()
                fed.reconcile_once()
                clock.advance(1.0)

            fed.reconcile_once()  # bootstrap split before serving
            # Overloaded: 300+500 wants vs 400 capacity.
            for _ in range(4):
                await round_once(
                    [(0, "a", 300.0), (1, "b", 500.0)]
                )
            rec = fed._reconcilers["strad-prop"]
            rec.lease_length = 3.0  # shorten the drain window
            share0 = shards[0]._straddle_shares["strad-prop"]
            share1 = shards[1]._straddle_shares["strad-prop"]
            assert abs(share0 - 150.0) < 1e-9
            assert abs(share1 - 250.0) < 1e-9

            # Partition shard 1 from the reconciler.
            fed.blocked = {1}
            frozen_total = []
            for _ in range(3):
                await round_once([(0, "a", 300.0), (1, "b", 500.0)])
                frozen_total.append(
                    shards[0]._straddle_shares["strad-prop"]
                )
            # While the lost share is frozen (ttl + drain window), the
            # survivor's share cannot grow into it.
            assert all(abs(v - 150.0) < 1e-9 for v in frozen_total)
            # The partitioned shard's capacity lease expired: it now
            # serves zero for the straddling resource.
            assert shards[1].resources["strad-prop"].capacity == 0.0
            # Σ installed shares never exceeded the configured 400.
            assert (
                shards[0]._straddle_shares["strad-prop"]
                + shards[1]._straddle_shares["strad-prop"]
                <= 400.0 + 1e-9
            )

            # Past expiry + drain window the slack re-offers: the
            # survivor's share grows to the whole pool.
            for _ in range(6):
                await round_once([(0, "a", 300.0)])
            assert (
                shards[0]._straddle_shares["strad-prop"] > 150.0 + 1e-9
            )
            assert (
                shards[0]._straddle_shares["strad-prop"] <= 400.0 + 1e-9
            )

            # Heal: shard 1 rejoins and the shares reconverge to the
            # demand-proportional split.
            fed.blocked = set()
            for _ in range(4):
                await round_once([(0, "a", 300.0), (1, "b", 500.0)])
            assert (
                abs(shards[0]._straddle_shares["strad-prop"] - 150.0)
                < 1e-9
            )
            assert (
                abs(shards[1]._straddle_shares["strad-prop"] - 250.0)
                < 1e-9
            )
        finally:
            for server in shards.values():
                await server.stop()

    run(body())


# ----------------------------------------------------------------------
# Per-shard persistence namespaces + warm takeover
# ----------------------------------------------------------------------


def test_parse_backend_namespace_scopes_file_layout(tmp_path):
    root = str(tmp_path / "persist")
    b0 = parse_backend(f"file:{root}", namespace="shard0")
    b1 = parse_backend(f"file:{root}", namespace="shard1")
    b0.write_snapshot(b"zero")
    b1.write_snapshot(b"one")
    assert b0.read_snapshot() == b"zero"
    assert b1.read_snapshot() == b"one"
    assert (tmp_path / "persist" / "shard0" / "snapshot.bin").exists()
    assert (tmp_path / "persist" / "shard1" / "snapshot.bin").exists()
    with pytest.raises(ValueError):
        parse_backend(f"file:{root}", namespace="../evil")


def test_per_shard_warm_takeover_restores_only_the_shard(tmp_path):
    async def body():
        clock = FakeClock()
        backends = {0: MemoryBackend(), 1: MemoryBackend()}

        async def make(name, shard, backend):
            server = CapacityServer(
                name, TrivialElection(), mode="immediate",
                minimum_refresh_interval=0.0, clock=clock, shard=shard,
                persist=PersistManager(
                    backend, snapshot_interval=1.0,
                    flush_interval=1.0, clock=clock,
                ),
                flightrec_capacity=0,
            )
            await server.load_config(_parity_repo())
            await asyncio.sleep(0)
            return server

        a0 = await make("shard0-a", 0, backends[0])
        b1 = await make("shard1-a", 1, backends[1])
        try:
            # Each shard serves ITS resources (router split).
            a0._decide("solo-a", Request("c0", 0.0, 30.0))
            b1._decide("solo-b", Request("c1", 0.0, 40.0))
            clock.advance(2.0)
            a0.persist_step()
            b1.persist_step()
            # Shard 0's master steps down cleanly; a fresh candidate of
            # the SAME shard (same namespace backend) takes over warm.
            await a0._on_is_master(False)
            a1 = await make("shard0-b", 0, backends[0])
            assert a1.last_restore is not None
            assert a1.last_restore["mode"] == "warm"
            assert a1.last_restore["leases_restored"] == 1
            # Exactly shard 0's slice: solo-a restored, nothing of
            # shard 1's ever seen.
            assert "solo-a" in a1.resources
            assert "solo-b" not in a1.resources
            assert a1.resources["solo-a"].store.get("c0").has == 30.0
            # Shard 1 is untouched by the sibling's takeover.
            assert b1.resources["solo-b"].store.get("c1").has == 40.0
            await a1.stop()
        finally:
            await a0.stop()
            await b1.stop()

    run(body())


# ----------------------------------------------------------------------
# Aggregation adapter (device-backed intermediate tick)
# ----------------------------------------------------------------------


def test_aggregation_adapter_matches_store_aggregation():
    rng = np.random.default_rng(3)
    agg = AggregationTickAdapter(dtype=np.float64)
    expect = {}
    for r in range(9):
        n = int(rng.integers(1, 40))
        wants = rng.integers(0, 50, n).astype(np.float64)
        weights = rng.integers(1, 4, n).astype(np.float64)
        bands = rng.integers(0, 3, n).astype(np.int32)
        agg.update(f"res{r}", wants, weights, bands)
        rows = {}
        for w, s, b in zip(wants, weights, bands):
            acc = rows.setdefault(int(b), [0.0, 0.0])
            acc[0] += w
            acc[1] += s
        expect[f"res{r}"] = sorted(
            (b, w, int(round(s))) for b, (w, s) in rows.items() if w > 0
        )
    out = agg.step()
    assert set(out) == {r for r in expect if expect[r]}
    for rid, bands in out.items():
        got = [(b, w, c) for b, w, c in bands]
        want = expect[rid]
        assert [b for b, *_ in got] == [b for b, *_ in want]
        for (gb, gw, gc), (wb, ww, wc) in zip(got, want):
            # Integer wants: the device summation is exact.
            assert gw == ww and gc == wc, (rid, got, want)
    # The band-masked summation is its own engine phase.
    assert agg.phase_s["aggregate"] > 0.0
    assert agg.ticks == 1

    # Dirty-row path: move one resource, the rest stay as last landed.
    agg.update("res0", [5.0], [1.0], [7])
    out = agg.step()
    assert out["res0"] == [(7, 5.0, 1)]
    assert out.get("res1") == expect["res1"]
    assert agg.ticks == 2


# ----------------------------------------------------------------------
# Federated intermediate end to end (loopback gRPC)
# ----------------------------------------------------------------------

ROOT_CONFIG_YAML = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
"""


def capacity_request(client_id, resource_id, wants, priority=0):
    req = pb.GetCapacityRequest(client_id=client_id)
    rr = req.resource.add()
    rr.resource_id = resource_id
    rr.wants = wants
    rr.priority = priority
    return req


def test_federated_intermediate_fans_out_per_shard():
    from doorman_tpu.server.config import parse_yaml_config

    async def body():
        roots = {}
        addrs = {}
        for shard in (0, 1):
            server = CapacityServer(
                f"root{shard}", TrivialElection(),
                minimum_refresh_interval=0.0, shard=shard,
                flightrec_capacity=0,
            )
            port = await server.start(0, host="127.0.0.1")
            await server.load_config(parse_yaml_config(ROOT_CONFIG_YAML))
            await asyncio.sleep(0)
            server.current_master = f"127.0.0.1:{port}"
            roots[shard] = server
            addrs[shard] = f"127.0.0.1:{port}"

        router = ShardRouter(2)
        # res1 -> shard 0, res0 -> shard 1 (pinned hash values above).
        assert router.shard_of("res1") == 0
        assert router.shard_of("res0") == 1

        async def resolver(shard, seeds):
            return addrs[shard]

        discovery = ShardDiscovery(
            {0: addrs[0], 1: addrs[1]}, resolver=resolver
        )
        inter = FederatedIntermediate(
            "inter", TrivialElection(),
            router=router, discovery=discovery,
            minimum_refresh_interval=0.0,
            flightrec_capacity=0,
        )
        port = await inter.start(0, host="127.0.0.1")
        await asyncio.sleep(0)
        inter.current_master = f"127.0.0.1:{port}"
        # Cancel the background updater: the test drives the upstream
        # exchange explicitly for determinism.
        for t in inter._tasks:
            t.cancel()
        inter._tasks.clear()
        try:
            inter.became_master_at -= 1000  # learning off
            async with grpc.aio.insecure_channel(
                f"127.0.0.1:{port}"
            ) as ch:
                stub = CapacityStub(ch)
                grants = {}
                for _ in range(40):
                    for rid in ("res0", "res1"):
                        res = inter.resources.get(rid)
                        if res is not None:
                            res.learning_mode_end = 0.0
                    o0 = await stub.GetCapacity(
                        capacity_request("ca", "res0", 40.0)
                    )
                    o1 = await stub.GetCapacity(
                        capacity_request("cb", "res1", 30.0)
                    )
                    grants = {
                        "res0": o0.response[0].gets.capacity,
                        "res1": o1.response[0].gets.capacity,
                    }
                    await inter._perform_parent_requests(0)
                    if grants == {"res0": 40.0, "res1": 30.0}:
                        break
                assert grants == {"res0": 40.0, "res1": 30.0}, grants

            # Each root shard saw ONLY its own resource, as a band
            # sub-lease from the intermediate.
            assert "res0" in roots[1].resources
            assert "res0" not in roots[0].resources
            assert "res1" in roots[0].resources
            assert "res1" not in roots[1].resources
            # The upstream exchange was a per-shard fan-out, counted in
            # the federation stats, and the aggregation ran as device
            # ticks through the engine phase streams.
            assert inter.fed_stats["upstream_rpcs"] >= 2
            assert inter.aggregator.ticks >= 1
            assert inter.aggregator.phase_s["aggregate"] > 0.0
            assert inter.status()["federation"]["upstream_rpcs"] >= 2
        finally:
            await inter.stop()
            for server in roots.values():
                await server.stop()

    run(body())


# ----------------------------------------------------------------------
# Federated client fan-out
# ----------------------------------------------------------------------


def test_federated_client_fans_refreshes_to_owning_shards():
    from doorman_tpu.server.config import parse_yaml_config

    async def body():
        roots = {}
        addrs = {}
        for shard in (0, 1):
            server = CapacityServer(
                f"root{shard}", TrivialElection(),
                minimum_refresh_interval=0.0, shard=shard,
                flightrec_capacity=0,
            )
            port = await server.start(0, host="127.0.0.1")
            await server.load_config(parse_yaml_config(ROOT_CONFIG_YAML))
            await asyncio.sleep(0)
            server.current_master = f"127.0.0.1:{port}"
            roots[shard] = server
            addrs[shard] = f"127.0.0.1:{port}"

        router = ShardRouter(2, straddle=["shared"])
        resolutions = []

        async def resolver(shard, seeds):
            resolutions.append(shard)
            return addrs[shard]

        discovery = ShardDiscovery(
            {0: addrs[0], 1: addrs[1]}, resolver=resolver
        )
        client = FederatedClient(
            router, discovery, client_id="fc0", background=False,
            minimum_refresh_interval=0.0, max_retries=0,
        )
        try:
            # res1 -> shard 0, res0 -> shard 1; "shared" straddles and
            # takes a placement override.
            await client.resource("res1", 30.0)
            await client.resource("res0", 40.0)
            await client.resource("shared", 10.0, shard=0)
            with pytest.raises(ValueError):
                await client.resource("res2", 5.0, shard=1)  # owner is 0
            assert await client.refresh_once()
            assert client.current_capacity("res1") == 30.0
            assert client.current_capacity("res0") == 40.0
            assert client.current_capacity("shared") == 10.0
            # One bulk refresh per owning shard, one Discovery
            # resolution per shard for the whole claim set — the
            # fan-out never re-resolves per refresh.
            assert sorted(resolutions) == [0, 1]
            await client.refresh_once()
            assert sorted(resolutions) == [0, 1]
            # Leases landed on the owning shards only.
            assert "res1" in roots[0].resources
            assert "res1" not in roots[1].resources
            assert "res0" in roots[1].resources
            assert "shared" in roots[0].resources  # placement override
        finally:
            await client.close()
            for server in roots.values():
                await server.stop()

    run(body())


# ----------------------------------------------------------------------
# The shard_partition chaos plan (federation-specific arc; the generic
# invariant smoke in test_chaos_smoke.py also runs every plan)
# ----------------------------------------------------------------------


def test_shard_partition_emits_federation_partition_instant():
    # The chaos seam marks partition onset on the trace timeline with
    # the registered `federation.partition` instant (obs/trace.py
    # KNOWN_INSTANT_NAMES; doormanlint registry-coherence pins that the
    # registry entry has a live emitter).
    from doorman_tpu.chaos import ChaosRunner, get_plan
    from doorman_tpu.obs import trace as trace_mod

    tracer = trace_mod.default_tracer()
    tracer.enable()
    try:
        verdict = asyncio.run(ChaosRunner(get_plan("shard_partition")).run())
        assert verdict["ok"]
        marks = [
            e for e in tracer.snapshot() if e.name == "federation.partition"
        ]
        assert marks, "partition onset never hit the trace timeline"
        assert marks[0].args["shards"] == [1]  # the plan partitions s1
    finally:
        tracer.disable()
        tracer.clear()


def test_shard_partition_plan_arc_and_determinism():
    from doorman_tpu.chaos import ChaosRunner, get_plan

    def run_plan():
        return asyncio.run(ChaosRunner(get_plan("shard_partition")).run())

    v1 = run_plan()
    v2 = run_plan()
    assert v1["ok"], v1["event_log"]
    assert v1["violations"] == []
    # Deterministic: same plan + seed replays the same event log.
    assert v1["log_sha256"] == v2["log_sha256"]

    log = v1["event_log"]
    # Per-shard mastership: all three shards are master at once.
    assert [e for e in log if e[1] == "master"][0][2] == [
        "s0", "s1", "s2",
    ]
    # The straddle shares converge to the demand-proportional split
    # before the fault...
    straddles = [e for e in log if e[1] == "straddle"]
    assert [[0, 22.5], [1, 22.5], [2, 45.0]] in [e[3] for e in straddles]
    fault_tick = next(e[0] for e in log if e[1] == "fault")
    # ...the partitioned shard drops out of the installed set while the
    # survivors' shares hold (blast radius)...
    during = [e[3] for e in straddles if e[0] >= fault_tick][0]
    assert during == [[0, 22.5], [2, 45.0]]
    # ...the fault visibly bit (the partitioned shard's client decayed
    # with its share)...
    assert any(e[1] == "degraded" for e in log)
    # ...and heal re-grants the lost share and reconverges in budget.
    assert v1["converged_after_heal_ticks"] is not None
    after = [e[3] for e in straddles if e[0] >= v1["heal_tick"]]
    assert [[0, 22.5], [1, 22.5], [2, 45.0]] in after
    # The flight recorder's federation beat: per-shard straddle
    # capacity tracks freeze-then-vanish for s1.
    recs = v1["flightrec_dump"]
    assert recs is None  # clean run: no violation dump
