"""Test configuration: force an 8-device virtual CPU platform so the
multi-chip sharding paths (shard_map over a Mesh) are exercised without TPU
hardware, and enable f64 for the parity math.

Note: a pytest plugin imports jax before this conftest runs, so the env-var
route is too late for jax.config defaults — but the XLA backend itself is
not initialized until first use, so jax.config.update and XLA_FLAGS still
take effect here."""

import os

import jax

# grpc's C++ threads write INFO lines (GOAWAY notices and the like)
# straight to fd 2, bypassing pytest capture; under `2>&1` they splice
# into the progress dot-lines and corrupt the tier-1 DOTS_PASSED count.
# Only ERROR-severity output is worth that interleaving.
os.environ.setdefault("GRPC_VERBOSITY", "ERROR")

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soaks excluded from tier-1 (deselected by -m 'not slow')",
    )
    config.addinivalue_line(
        "markers",
        "perf: micro-benchmark assertions (loose budgets; run in tier-1 "
        "to keep instrumentation overhead honest)",
    )
