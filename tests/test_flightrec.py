"""Flight recorder: ring semantics, dumps (JSON + Chrome overlay),
server integration (per-tick records, tick-exception auto-dump,
/debug/slo and /debug/flightrec endpoints), and determinism — a forced
chaos invariant violation dumps the last N ticks byte-stably across
two runs of the same seeded plan."""

import asyncio
import json
import os
import urllib.request

import tests.conftest  # noqa: F401

from doorman_tpu.chaos.plan import FaultEvent, FaultPlan
from doorman_tpu.chaos.runner import ChaosRunner
from doorman_tpu.obs.debug import DebugServer
from doorman_tpu.obs.flightrec import FlightRecorder, store_digest
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
"""


def fetch(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read().decode()


# ----------------------------------------------------------------------
# Ring semantics
# ----------------------------------------------------------------------


def test_ring_bounds_and_sequence():
    fr = FlightRecorder(4, component="t", dump_dir="")
    assert fr.occupancy == 0 and fr.head_seq == 0
    for i in range(10):
        fr.record(t=float(i), tick=i)
    assert fr.head_seq == 10
    assert fr.occupancy == 4
    assert [r["seq"] for r in fr.snapshot()] == [7, 8, 9, 10]
    st = fr.status()
    assert st["head_seq"] == 10 and st["capacity"] == 4
    assert st["last_dump"] is None


def test_view_is_side_effect_free_and_dump_writes_files(tmp_path):
    fr = FlightRecorder(
        8, component="t", clock=lambda: 123.0, dump_dir=str(tmp_path)
    )
    for i in range(3):
        fr.record(t=float(i), tick=i, wall_ms=2.0,
                  phases={"solve": 1.5, "apply": 0.5})
    view = fr.view("peek")
    assert len(view["records"]) == 3
    assert fr.last_dump is None and not list(tmp_path.iterdir())

    dump = fr.dump("tick_exception")
    assert dump["reason"] == "tick_exception"
    assert [r["tick"] for r in dump["records"]] == [0, 1, 2]
    assert fr.last_dump["reason"] == "tick_exception"
    names = sorted(p.name for p in tmp_path.iterdir())
    assert len(names) == 2
    assert names[0].endswith(".json") and names[1].endswith(".trace.json")
    # Both artifacts parse; the overlay carries the tick events.
    on_disk = json.loads((tmp_path / names[0]).read_text())
    assert on_disk["records"] == dump["records"]
    overlay = json.loads((tmp_path / names[1]).read_text())
    ticks = [e for e in overlay["traceEvents"]
             if e.get("name") == "tick" and e.get("ph") == "X"]
    assert len(ticks) == 3


def test_chrome_overlay_counters_and_instants():
    fr = FlightRecorder(8, component="t", dump_dir="")
    fr.record(t=0.0, tick=0, wall_ms=3.0, phases={"solve": 3.0},
              admission_level=0.5, shed_by_band={"0": 7})
    fr.record(t=1.0, tick=1, error="RuntimeError: boom")
    overlay = json.loads(fr.chrome_overlay())
    names = [e["name"] for e in overlay["traceEvents"]]
    assert "admission_level" in names and "shed_by_band" in names
    assert "solve" in names and "error" in names


def test_store_digest_tracks_grant_mass():
    class Store:
        def __init__(self, has):
            self.sum_has, self.sum_wants = has, 10.0

        def __len__(self):
            return 1

    class Res:
        def __init__(self, has):
            self.capacity, self.store = 100.0, Store(has)

    a = store_digest({"r0": Res(5.0)})
    assert a == store_digest({"r0": Res(5.0)})  # stable
    assert a != store_digest({"r0": Res(6.0)})  # moves with grants
    assert len(a) == 16


# ----------------------------------------------------------------------
# Server integration
# ----------------------------------------------------------------------


def test_server_records_ticks_and_serves_debug_pages(tmp_path):
    async def body():
        server = CapacityServer(
            "fr-server", TrivialElection(), mode="batch",
            tick_interval=3600.0,  # ticks driven manually below
            minimum_refresh_interval=0.0,
            flightrec_capacity=16, flightrec_dir=str(tmp_path),
        )
        await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        from doorman_tpu.algorithms import Request

        server._decide("r0", Request("c1", 0.0, 30.0, 1))
        await server.tick_once()
        await server.tick_once()

        recs = server.flightrec.snapshot()
        assert len(recs) == 2
        assert recs[-1]["tick"] == 2
        assert recs[-1]["wall_ms"] >= 0.0
        assert recs[-1]["resources"] == 1
        assert len(recs[-1]["digest"]) == 16
        assert recs[-1]["epoch"] >= 1  # TrivialElection's one flip

        st = server.status()
        assert st["flightrec"]["head_seq"] == 2
        assert st["flightrec"]["occupancy"] == 2
        assert st["slo"] is None  # not evaluated yet

        verdicts = {v["slo"]: v for v in server.evaluate_slos()}
        assert verdicts["tick_budget_p50_ms"]["status"] in (
            "pass", "fail"  # measured either way — never no_data
        )
        assert verdicts["top_band_goodput"]["status"] == "no_data"
        assert server.status()["slo"]["verdicts"]

        debug = DebugServer(host="127.0.0.1")
        debug.add_server(server, asyncio.get_running_loop())
        dport = debug.start()
        loop = asyncio.get_running_loop()

        status, page = await loop.run_in_executor(
            None, fetch, dport, "/debug"
        )
        assert "/debug/slo" in page and "/debug/flightrec" in page

        status, body_ = await loop.run_in_executor(
            None, fetch, dport, "/debug/slo?format=json"
        )
        assert status == 200
        slo_json = json.loads(body_)["fr-server"]
        assert {v["slo"] for v in slo_json["verdicts"]} >= {
            "tick_budget_p50_ms", "get_capacity_p99_ms"
        }

        status, body_ = await loop.run_in_executor(
            None, fetch, dport, "/debug/flightrec?format=json"
        )
        assert status == 200
        dump = json.loads(body_)["fr-server"]
        assert [r["tick"] for r in dump["records"]] == [1, 2]

        status, body_ = await loop.run_in_executor(
            None, fetch, dport, "/debug/flightrec?format=chrome"
        )
        assert status == 200
        assert json.loads(body_)["traceEvents"]

        for path in ("/debug/slo", "/debug/flightrec", "/debug/status"):
            status, page = await loop.run_in_executor(
                None, fetch, dport, path
            )
            assert status == 200, path
        # The status overview carries the satellite surfaces.
        _, page = await loop.run_in_executor(
            None, fetch, dport, "/debug/status"
        )
        assert "flight recorder: head seq" in page
        assert "last SLO verdict" in page

        debug.stop()
        await server.stop()

    asyncio.run(body())


def test_tick_exception_auto_dumps(tmp_path):
    async def body():
        server = CapacityServer(
            "fr-crash", TrivialElection(), mode="batch",
            tick_interval=3600.0, minimum_refresh_interval=0.0,
            flightrec_capacity=8, flightrec_dir=str(tmp_path),
        )
        await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        from doorman_tpu.algorithms import Request

        server._decide("r0", Request("c1", 0.0, 30.0, 1))
        await server.tick_once()  # one healthy record

        async def boom():
            raise RuntimeError("device tunnel died")

        server._tick_once_locked = boom
        try:
            await server.tick_once()
            raise AssertionError("tick_once must re-raise")
        except RuntimeError:
            pass

        assert server.flightrec.last_dump["reason"] == "tick_exception"
        recs = server.flightrec.snapshot()
        assert "RuntimeError: device tunnel died" in recs[-1]["error"]
        dumped = [
            p for p in os.listdir(tmp_path)
            if "tick_exception" in p and p.endswith(".json")
            and not p.endswith(".trace.json")
        ]
        assert len(dumped) == 1
        on_disk = json.loads((tmp_path / dumped[0]).read_text())
        # The dump replays the healthy tick AND the failing one.
        assert len(on_disk["records"]) == 2
        await server.stop()

    asyncio.run(body())


def test_flightrec_disabled_is_clean():
    async def body():
        server = CapacityServer(
            "fr-off", TrivialElection(), mode="batch",
            tick_interval=3600.0, minimum_refresh_interval=0.0,
            flightrec_capacity=0,
        )
        await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        from doorman_tpu.algorithms import Request

        server._decide("r0", Request("c1", 0.0, 30.0, 1))
        await server.tick_once()
        assert server.flightrec is None
        assert server.status()["flightrec"] is None
        # SLO evaluation still works; the tick stream is just absent.
        verdicts = {v["slo"]: v for v in server.evaluate_slos()}
        assert verdicts["tick_budget_p50_ms"]["status"] == "no_data"
        await server.stop()

    asyncio.run(body())


# ----------------------------------------------------------------------
# Chaos determinism: the black box is a replay artifact
# ----------------------------------------------------------------------


def _noheal_plan():
    """A fault window that outlives the run: no reconvergence is
    possible, so the end-of-run reconvergence violation fires — the
    deterministic way to force an invariant violation."""
    return FaultPlan(
        name="forced_noheal", seed=9,
        setup={"servers": 1, "clients": 2, "wants": [10.0, 20.0],
               "capacity": 50, "mode": "immediate", "lease_length": 60,
               "refresh_interval": 1, "learning_mode_duration": 0,
               "election_ttl": 3.0},
        events=[FaultEvent(at_tick=4, kind="kv_drop", target="s0",
                           duration_ticks=40)],
        warmup_ticks=4, total_ticks=12, reconverge_ticks=2,
    )


def test_forced_violation_dumps_byte_stably(monkeypatch):
    # The dump must not depend on the environment's dump directory.
    monkeypatch.delenv("DOORMAN_FLIGHTREC_DIR", raising=False)
    v1 = asyncio.run(ChaosRunner(_noheal_plan()).run())
    v2 = asyncio.run(ChaosRunner(_noheal_plan()).run())
    assert not v1["ok"]
    dump = v1["flightrec_dump"]
    assert dump is not None
    assert dump["reason"] == "invariant:reconvergence"
    # The dump replays every tick of the run plus the end-of-run entry.
    plan = _noheal_plan()
    assert [r["tick"] for r in dump["records"]] == list(
        range(plan.total_ticks + 1)
    )
    assert dump["records"][-1]["violations"][0][1] == "reconvergence"
    # Per-tick records carry the black-box fields.
    rec = dump["records"][0]
    assert rec["masters"] == ["s0"]
    assert "digests" in rec and "s0" in rec["digests"]
    # Byte-stable across two runs of the same seeded plan.
    assert json.dumps(dump, sort_keys=True) == json.dumps(
        v2["flightrec_dump"], sort_keys=True
    )
    # The SLO block reports the blown budget as a hard fail.
    slo_v = {x["slo"]: x for x in v1["slo"]["verdicts"]}
    assert slo_v["forced_noheal:reconverge_ticks"]["status"] == "fail"
    assert not v1["slo"]["ok"]


def test_clean_run_has_no_dump_and_passing_slo(monkeypatch):
    monkeypatch.delenv("DOORMAN_FLIGHTREC_DIR", raising=False)
    from doorman_tpu.chaos.plans import get_plan

    v = asyncio.run(ChaosRunner(get_plan("master_flap")).run())
    assert v["ok"]
    assert v["flightrec_dump"] is None
    slo_v = {x["slo"]: x for x in v["slo"]["verdicts"]}
    assert slo_v["master_flap:reconverge_ticks"]["status"] == "pass"
    assert v["slo"]["ok"]
