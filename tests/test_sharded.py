"""Sharded-solve tests on the 8-device virtual CPU mesh: the psum-combined
solve must match the single-chip solve exactly, on one- and two-axis
meshes."""

import numpy as np
import pytest

import tests.conftest  # noqa: F401
import jax

from doorman_tpu.parallel import make_mesh, make_sharded_solver, shard_edges
from doorman_tpu.parallel.sharded import dc_aggregates, replicate_resources
from doorman_tpu.solver import solve_tick
from tests.test_solver_kernels import build_batch


def random_tables(seed, n_resources=12, max_clients=40):
    rng = np.random.default_rng(seed)
    tables = []
    for _ in range(n_resources):
        n = int(rng.integers(1, max_clients))
        tables.append(
            {
                "kind": int(rng.integers(0, 5)),
                "capacity": float(rng.integers(1, 500)),
                "static_cap": float(rng.integers(1, 100)),
                "wants": rng.integers(0, 200, n).astype(np.float64).tolist(),
                "has": rng.integers(0, 100, n).astype(np.float64).tolist(),
                "sub": rng.integers(1, 8, n).astype(np.float64).tolist(),
            }
        )
    return tables


def test_eight_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("seed", range(3))
def test_sharded_matches_single_chip(seed):
    tables = random_tables(seed)
    edges, resources = build_batch(tables, pad_edges=64)
    expected = np.asarray(solve_tick(edges, resources))

    mesh = make_mesh()
    solve = make_sharded_solver(mesh)
    sharded = shard_edges(mesh, edges)
    replicated = replicate_resources(mesh, resources)
    gets = np.asarray(solve(sharded, replicated))
    np.testing.assert_array_equal(gets[: expected.shape[0]], expected)
    assert np.all(gets[expected.shape[0] :] == 0.0)


def test_two_level_tree_mesh_matches():
    tables = random_tables(7)
    edges, resources = build_batch(tables, pad_edges=64)
    expected = np.asarray(solve_tick(edges, resources))

    mesh = make_mesh([2, 4], ("dc", "clients"))
    solve = make_sharded_solver(mesh)
    gets = np.asarray(
        solve(shard_edges(mesh, edges), replicate_resources(mesh, resources))
    )
    np.testing.assert_array_equal(gets[: expected.shape[0]], expected)


def test_dc_aggregates_match_global_sums():
    tables = random_tables(11, n_resources=6)
    edges, resources = build_batch(tables, pad_edges=64)
    mesh = make_mesh([2, 4], ("dc", "clients"))
    sharded = shard_edges(mesh, edges)
    w, h, s = dc_aggregates(mesh, sharded, resources.num_resources)
    assert w.shape == (2, resources.num_resources)
    # Summing the per-dc band tables reproduces the global aggregates —
    # the root sees the same totals the intermediate reports imply.
    rid = np.asarray(edges.resource)
    active = np.asarray(edges.active)
    for r in range(len(tables)):
        mask = (rid == r) & active
        np.testing.assert_allclose(
            np.asarray(w).sum(axis=0)[r], np.asarray(edges.wants)[mask].sum()
        )
        np.testing.assert_allclose(
            np.asarray(s).sum(axis=0)[r],
            np.asarray(edges.subclients)[mask].sum(),
        )
