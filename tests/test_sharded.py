"""Sharded-solve tests on the 8-device virtual CPU mesh: the psum-combined
solve must match the single-chip solve exactly, on one- and two-axis
meshes."""

import numpy as np
import pytest

import tests.conftest  # noqa: F401
import jax

from doorman_tpu.parallel import make_mesh, make_sharded_solver, shard_edges
from doorman_tpu.parallel.sharded import dc_aggregates, replicate_resources
from doorman_tpu.solver import solve_tick
from tests.test_solver_kernels import build_batch


def random_tables(seed, n_resources=12, max_clients=40):
    rng = np.random.default_rng(seed)
    tables = []
    for _ in range(n_resources):
        n = int(rng.integers(1, max_clients))
        tables.append(
            {
                "kind": int(rng.integers(0, 5)),
                "capacity": float(rng.integers(1, 500)),
                "static_cap": float(rng.integers(1, 100)),
                "wants": rng.integers(0, 200, n).astype(np.float64).tolist(),
                "has": rng.integers(0, 100, n).astype(np.float64).tolist(),
                "sub": rng.integers(1, 8, n).astype(np.float64).tolist(),
            }
        )
    return tables


def test_eight_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("seed", range(3))
def test_sharded_matches_single_chip(seed):
    tables = random_tables(seed)
    edges, resources = build_batch(tables, pad_edges=64)
    expected = np.asarray(solve_tick(edges, resources))

    mesh = make_mesh()
    solve = make_sharded_solver(mesh)
    sharded = shard_edges(mesh, edges)
    replicated = replicate_resources(mesh, resources)
    gets = np.asarray(solve(sharded, replicated))
    np.testing.assert_array_equal(gets[: expected.shape[0]], expected)
    assert np.all(gets[expected.shape[0] :] == 0.0)


def test_two_level_tree_mesh_matches():
    tables = random_tables(7)
    edges, resources = build_batch(tables, pad_edges=64)
    expected = np.asarray(solve_tick(edges, resources))

    mesh = make_mesh([2, 4], ("dc", "clients"))
    solve = make_sharded_solver(mesh)
    gets = np.asarray(
        solve(shard_edges(mesh, edges), replicate_resources(mesh, resources))
    )
    np.testing.assert_array_equal(gets[: expected.shape[0]], expected)


def test_dc_aggregates_match_global_sums():
    tables = random_tables(11, n_resources=6)
    edges, resources = build_batch(tables, pad_edges=64)
    mesh = make_mesh([2, 4], ("dc", "clients"))
    sharded = shard_edges(mesh, edges)
    w, h, s = dc_aggregates(mesh, sharded, resources.num_resources)
    assert w.shape == (2, resources.num_resources)
    # Summing the per-dc band tables reproduces the global aggregates —
    # the root sees the same totals the intermediate reports imply.
    rid = np.asarray(edges.resource)
    active = np.asarray(edges.active)
    for r in range(len(tables)):
        mask = (rid == r) & active
        np.testing.assert_allclose(
            np.asarray(w).sum(axis=0)[r], np.asarray(edges.wants)[mask].sum()
        )
        np.testing.assert_allclose(
            np.asarray(s).sum(axis=0)[r],
            np.asarray(edges.subclients)[mask].sum(),
        )


def test_sharded_dense_matches_single_chip():
    """Resource-axis sharded dense solve (no collectives) must equal the
    unsharded dense solve; R=23 exercises shard_dense's row padding."""
    from doorman_tpu.parallel import make_sharded_dense_solver, shard_dense
    from doorman_tpu.solver.dense import DenseBatch, solve_dense

    rng = np.random.default_rng(3)
    R, K, C = 23, 128, 100  # pads to 24 rows over 8 devices
    active = np.zeros((R, K), bool)
    active[:, :C] = True
    mesh = make_mesh([8], ("clients",), jax.devices()[:8])
    host = DenseBatch(
        wants=(rng.integers(0, 100, (R, K)) * active).astype(np.float64),
        has=(rng.integers(0, 50, (R, K)) * active).astype(np.float64),
        subclients=active.astype(np.float64),
        active=active,
        capacity=rng.integers(100, 10_000, R).astype(np.float64),
        algo_kind=rng.integers(0, 5, R).astype(np.int32),
        learning=rng.random(R) < 0.2,
        static_capacity=rng.integers(1, 100, R).astype(np.float64),
    )
    batch = shard_dense(mesh, host)
    solver = make_sharded_dense_solver(mesh, donate=True)
    got = np.asarray(solver(batch))
    batch2 = shard_dense(mesh, host)  # donated buffers are consumed
    expected = np.asarray(jax.jit(solve_dense)(batch2))
    np.testing.assert_allclose(got[:R], expected[:R], rtol=1e-12,
                               atol=1e-12)
    assert (got[R:] == 0).all()  # padded rows are inactive


def test_sharded_priority_matches_single_chip():
    """PRIORITY_BANDS sharded over the mesh: group caps are the one
    cross-resource coupling, combined with a psum per bisection
    evaluation — the result must match the unsharded solve including
    the cap enforcement. R=21 exercises shard_priority's padding (to 24
    over 8 devices) with ungrouped (-1) fill rows."""
    from doorman_tpu.parallel import (
        make_sharded_priority_solver,
        shard_priority,
    )
    from doorman_tpu.solver.priority import PriorityBatch, solve_priority

    rng = np.random.default_rng(9)
    R, K, G = 21, 64, 3
    active = np.zeros((R, K), bool)
    for r in range(R):
        active[r, : rng.integers(1, K)] = True
    capacity = rng.integers(100, 5000, R).astype(np.float64)
    group = rng.choice(np.array([-1, 0, 1, 2], np.int32), R)
    group_cap = np.asarray(
        [
            max(capacity[group == g].sum() * 0.4, 1.0)
            for g in range(G)
        ],
        np.float64,
    )
    host = PriorityBatch(
        wants=(rng.integers(0, 200, (R, K)) * active).astype(np.float64),
        weights=(rng.integers(1, 4, (R, K)) * active).astype(np.float64),
        band=(rng.integers(0, 4, (R, K)) * active).astype(np.int32),
        active=active,
        capacity=capacity,
        group=group,
        group_cap=group_cap,
    )
    mesh = make_mesh([8], ("clients",), jax.devices()[:8])
    got = np.asarray(
        make_sharded_priority_solver(mesh, num_bands=4)(
            shard_priority(mesh, host)
        )
    )
    expected = np.asarray(solve_priority(host, num_bands=4))
    np.testing.assert_allclose(got[:R], expected, rtol=1e-9, atol=1e-9)
    assert (got[R:] == 0).all()  # padded rows inactive and ungrouped
    # The caps hold on the sharded result.
    for g in range(G):
        usage = got[:R][group == g].sum()
        assert usage <= group_cap[g] * (1 + 1e-9) + 1e-6


def test_sharded_chunked_matches_single_chip():
    """Chunk-row sharded WIDE solve: resources span chunk rows that
    land on DIFFERENT devices, so per-segment totals need the psum —
    must equal the single-device chunked solve and stay zero on the
    padding rows."""
    from doorman_tpu.parallel.sharded import (
        make_sharded_chunked_solver,
        shard_chunked,
    )
    from doorman_tpu.solver.dense import ChunkedDenseBatch, solve_chunked

    rng = np.random.default_rng(9)
    K = 16
    # 3 wide resources of 5/7/2 chunks + 1 padding segment = 14 rows
    # (pads to 16 over 8 devices); every resource's chunks straddle a
    # device boundary somewhere.
    n_chunks = [5, 7, 2]
    S = len(n_chunks) + 1  # + padding segment
    R = sum(n_chunks)
    row_seg = np.repeat(np.arange(len(n_chunks)), n_chunks).astype(np.int32)
    counts = [int(rng.integers((n - 1) * K + 1, n * K + 1))
              for n in n_chunks]
    active = np.zeros((R, K), bool)
    base = 0
    for seg, (n, cnt) in enumerate(zip(n_chunks, counts)):
        slots = np.arange(cnt)
        active[base + slots // K, slots % K] = True
        base += n
    host = ChunkedDenseBatch(
        wants=(rng.integers(0, 100, (R, K)) * active).astype(np.float64),
        has=(rng.integers(0, 50, (R, K)) * active).astype(np.float64),
        subclients=active.astype(np.float64),
        active=active,
        row_seg=row_seg,
        capacity=np.append(
            rng.integers(100, 10_000, len(n_chunks)), 0.0
        ).astype(np.float64),
        algo_kind=np.append(
            np.array([2, 3, 4]), 0
        ).astype(np.int32),  # prop / fair / topup across devices
        learning=np.zeros(S, bool),
        static_capacity=np.zeros(S, np.float64),
    )
    mesh = make_mesh([8], ("clients",), jax.devices()[:8])
    batch = shard_chunked(mesh, host)
    solver = make_sharded_chunked_solver(mesh, donate=True)
    got = np.asarray(solver(batch))
    expected = np.asarray(jax.jit(solve_chunked)(host))
    np.testing.assert_allclose(got[:R], expected, rtol=1e-12, atol=1e-12)
    assert (got[R:] == 0).all()


def test_sharded_chunked_two_axis_mesh():
    from doorman_tpu.parallel.sharded import (
        make_sharded_chunked_solver,
        shard_chunked,
    )
    from doorman_tpu.solver.dense import ChunkedDenseBatch, solve_chunked

    rng = np.random.default_rng(21)
    K, R, S = 8, 6, 2  # one wide resource of 6 chunks + padding segment
    active = np.ones((R, K), bool)
    active[-1, 5:] = False
    host = ChunkedDenseBatch(
        wants=(rng.integers(1, 100, (R, K)) * active).astype(np.float64),
        has=np.zeros((R, K)),
        subclients=active.astype(np.float64),
        active=active,
        row_seg=np.zeros(R, np.int32),
        capacity=np.array([900.0, 0.0]),
        algo_kind=np.array([3, 0], np.int32),  # FAIR_SHARE waterfill
        learning=np.zeros(S, bool),
        static_capacity=np.zeros(S, np.float64),
    )
    mesh = make_mesh([2, 4], ("dc", "clients"), jax.devices()[:8])
    batch = shard_chunked(mesh, host)
    got = np.asarray(make_sharded_chunked_solver(mesh)(batch))
    expected = np.asarray(jax.jit(solve_chunked)(host))
    np.testing.assert_allclose(got[:R], expected, rtol=1e-12, atol=1e-12)
    # Oversubscribed fair share: grants fill the capacity exactly.
    np.testing.assert_allclose(got[:R].sum(), 900.0, rtol=1e-9)
