"""Chaos regression plans pinning recently patched behaviors.

Each test expresses its fault as a FaultPlan/FaultEvent (the chaos
subsystem's replay artifact) instead of a bespoke fixture:

  * transient election-renewal retry: the REAL EtcdKV election over the
    real v3 HTTP dialect (tests/fake_etcd) survives exactly one dropped
    keepalive round-trip — the patch that stopped small-TTL elections
    flapping under load;
  * stale-port detection: tools/drives ensure_ports_free fails LOUDLY
    when a leaked server still holds the port;
  * backend-probe retry classification: utils.backend.wait_for_backend
    rides out a transient tunnel blip but fails fast on unretryable
    environment breakage;
  * ResidentOverflow clears BOTH resident handles, so a fallback tick
    cannot be overwritten by one-tick-stale wide grants.
"""

import asyncio
import importlib.util
import pathlib

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.chaos import (
    ChaosEtcdGateway,
    FaultEvent,
    FaultPlan,
    FaultState,
    PortInjector,
    SolverInjector,
    backend_probe_argv,
)
from doorman_tpu.server.election import EtcdKV, KVElection
from doorman_tpu.utils.backend import wait_for_backend
from tests.fake_etcd import FakeEtcd

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_drive_common():
    spec = importlib.util.spec_from_file_location(
        "_drive_common", REPO / "tools" / "drives" / "_common.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_election_renewal_retry_survives_one_etcd_hiccup():
    """One dropped /v3/lease/keepalive round-trip must read as a
    transient failure (retried inside the renewal window), NOT as
    mastership loss. The fault is the plan's single event, scoped to
    the keepalive path so the election's watcher reads cannot absorb
    the budget."""
    plan = FaultPlan(
        name="renewal_hiccup",
        seed=0,
        setup={"election_ttl": 1.5},
        events=[
            FaultEvent(
                at_tick=0, kind="etcd_drop", target="etcd",
                duration_ticks=1,
                params={"calls": 1,
                        "path_prefix": "/v3/lease/keepalive"},
            )
        ],
        warmup_ticks=0,
        total_ticks=1,
    )
    fake = FakeEtcd()
    fake.start()
    state = FaultState(plan.seed)

    async def body():
        ttl = plan.setup["election_ttl"]
        gw = ChaosEtcdGateway([fake.address], state)
        election = KVElection(
            EtcdKV([fake.address], gateway=gw), "/chaos-lock", ttl=ttl
        )
        events = []
        won = asyncio.Event()

        async def on_is_master(is_master):
            events.append(is_master)
            if is_master:
                won.set()

        async def on_current(_):
            pass

        await election.run("candidate", on_is_master, on_current)
        await asyncio.wait_for(won.wait(), 10)
        # Arm the plan's fault: the next keepalive round-trip drops.
        for ev in plan.events_at(0):
            state.start(ev)
        # Ride through ~3 renewal cycles of real time.
        await asyncio.sleep(1.5 * ttl)
        assert events == [True], "one etcd hiccup read as mastership loss"
        assert fake.value("/chaos-lock") == "candidate"
        await election.stop()

    try:
        asyncio.run(body())
    finally:
        fake.stop()


def test_stale_port_detected_by_ensure_ports_free():
    """A 'leaked server' (the PortInjector holding the port, as a
    killed drive's zombie would) must make ensure_ports_free exit
    loudly; releasing the port clears the check."""
    plan = FaultPlan(
        name="stale_port",
        seed=0,
        setup={},
        events=[FaultEvent(at_tick=0, kind="port_bind",
                           duration_ticks=0)],
        warmup_ticks=0,
        total_ticks=1,
    )
    common = _load_drive_common()
    ports = PortInjector()
    try:
        bound = [ports.bind() for ev in plan.events_at(0)]
        assert bound
        with pytest.raises(SystemExit):
            common.ensure_ports_free(bound[0])
    finally:
        ports.release_all()
    common.ensure_ports_free(bound[0])  # freed: no complaint


def test_backend_probe_rides_out_transient_blip():
    """A fast RuntimeError probe failure (what a down tunnel surfaces)
    stays retryable: with the fault budgeted to one probe, the second
    attempt succeeds and wait_for_backend returns None."""
    state = FaultState(0)
    state.start(FaultEvent(
        at_tick=0, kind="backend_probe_fail", duration_ticks=10,
        params={"calls": 1, "mode": "tunnel_down"},
    ))
    reason = wait_for_backend(
        attempts=2, per_timeout_s=0.5,
        probe_argv=lambda: backend_probe_argv(state),
    )
    assert reason is None


def test_backend_probe_fails_fast_on_unretryable_breakage():
    """Environment breakage (ModuleNotFoundError) must NOT burn the
    paced retry schedule — it reports within one attempt."""
    state = FaultState(0)
    state.start(FaultEvent(
        at_tick=0, kind="backend_probe_fail", duration_ticks=10,
        params={"mode": "unretryable"},
    ))
    reason = wait_for_backend(
        attempts=3, per_timeout_s=30.0,
        probe_argv=lambda: backend_probe_argv(state),
    )
    assert reason is not None and "ModuleNotFoundError" in reason


def test_resident_overflow_clears_both_resident_handles():
    """An injected ResidentOverflow takes the BatchSolver fallback and
    must drop BOTH in-flight handles — with a wide resource in the mix,
    a surviving pre-overflow wide handle would be collected next tick
    and overwrite the fresher batch-applied grants with one-tick-stale
    ones (the chunk-version guard only detects membership changes, not
    value staleness)."""
    from doorman_tpu import native

    if not native.native_available():
        pytest.skip("native engine unavailable")

    from doorman_tpu.algorithms import Request
    from doorman_tpu.server.config import parse_yaml_config
    from doorman_tpu.server.election import TrivialElection
    from doorman_tpu.server.server import CapacityServer
    from doorman_tpu.solver.batch import DENSE_MAX_K

    plan_event = FaultEvent(
        at_tick=0, kind="resident_overflow", target="s0",
        duration_ticks=1, params={"calls": 1},
    )

    async def body():
        state = FaultState(0)
        server = CapacityServer(
            "s0", TrivialElection(), mode="batch",
            native_store=True, minimum_refresh_interval=0.0,
        )
        SolverInjector(state, "s0").install(server)
        await server.load_config(parse_yaml_config(
            "resources:\n"
            "- identifier_glob: \"*\"\n"
            "  capacity: 100\n"
            "  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,\n"
            "              refresh_interval: 1, learning_mode_duration: 0}\n"
        ))
        await asyncio.sleep(0)
        for c, w in [("a", 60.0), ("b", 50.0)]:
            server._decide("narrow0", Request(c, 0.0, w, 1, priority=1))
        # A resource wider than the dense bucket cap: takes the chunked
        # wide solver, so a wide handle is genuinely in flight.
        wide = server.get_or_create_resource("wide0")
        for i in range(DENSE_MAX_K + 8):
            wide.store.assign(f"w{i}", 60.0, 1.0, 0.0, 1.0, 1)
        await server.tick_once()
        await server.tick_once()
        assert len(server._resident_pipe) > 0
        assert len(server._resident_wide_pipe) > 0
        state.start(plan_event)
        await server.tick_once()  # overflow -> BatchSolver fallback
        assert len(server._resident_pipe) == 0
        assert len(server._resident_wide_pipe) == 0, (
            "fallback tick left a stale wide handle collectable"
        )
        await server.stop()

    asyncio.run(body())
