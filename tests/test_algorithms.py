"""Scalar algorithm tests.

The table-driven cases reproduce the reference's exact grant numbers
(/root/reference/go/server/doorman/algorithm_test.go:64-312) — they are the
parity oracle for the per-request algorithms."""

import pytest

from doorman_tpu.algorithms import Request, get_algorithm
from doorman_tpu.core import LeaseStore
from doorman_tpu.proto import doorman_pb2 as pb


def make_algo(kind, lease=300, refresh=5, variant=None):
    algo = pb.Algorithm(kind=kind, lease_length=lease, refresh_interval=refresh)
    if variant:
        p = algo.parameters.add()
        p.name = "variant"
        p.value = variant
    return get_algorithm(algo)


def run_cases(kind, cases, capacity, *, respect_max=True, preload=True,
              variant=None):
    """cases: (client, has, wants, should_get, subclients)."""
    store = LeaseStore("test")
    algo = make_algo(kind, variant=variant)
    if preload:
        for client, has, wants, _, sub in cases:
            store.assign(client, 300, 5, has, wants, sub)
    for i, (client, has, wants, should_get, sub) in enumerate(cases):
        lease = algo(store, capacity, Request(client, has, wants, sub))
        assert lease.has == should_get, (
            f"case {i + 1} ({client}): got {lease.has}, want {should_get}"
        )
        if respect_max:
            assert store.sum_has <= capacity + 1e-9
    return store


def test_no_algorithm():
    store = run_cases(
        pb.Algorithm.NO_ALGORITHM,
        [("a", 0, 10, 10, 1), ("b", 0, 100, 100, 1)],
        0,
        respect_max=False,
        preload=False,
    )
    assert store.sum_has == 110


def test_static():
    run_cases(
        pb.Algorithm.STATIC,
        [("a", 0, 100, 100, 1), ("b", 0, 10, 10, 1), ("c", 0, 120, 100, 1)],
        100,
        respect_max=False,
        preload=False,
    )


def test_fair_share():
    run_cases(
        pb.Algorithm.FAIR_SHARE,
        [("c0", 0, 1000, 55, 1), ("c1", 0, 60, 55, 1), ("c2", 0, 10, 10, 1)],
        120,
    )


def test_fair_share_lower_extra():
    run_cases(
        pb.Algorithm.FAIR_SHARE,
        [("c0", 0, 1000, 60, 1), ("c1", 0, 50, 50, 1), ("c2", 0, 10, 10, 1)],
        120,
    )


def test_fair_share_multiple_subclients():
    run_cases(
        pb.Algorithm.FAIR_SHARE,
        [
            ("c0", 0, 1000, 60, 6),
            ("c1", 0, 500, 40, 4),
            ("c2", 0, 200, 20, 2),
        ],
        120,
    )
    run_cases(
        pb.Algorithm.FAIR_SHARE,
        [
            ("c0", 0, 2000, 200, 10),
            ("c1", 0, 500, 200, 10),
            ("c2", 0, 700, 600, 30),
        ],
        1000,
    )


def test_proportional_topup_variant():
    # The Go reference's PROPORTIONAL_SHARE tables (equal share + top-up),
    # selected with algorithm parameter variant=topup.
    run_cases(
        pb.Algorithm.PROPORTIONAL_SHARE,
        [("c0", 0, 60, 55, 1), ("c1", 0, 60, 55, 1), ("c2", 0, 10, 10, 1)],
        120,
        variant="topup",
    )
    # Unpreloaded: order matters; late small client finds nothing unused.
    run_cases(
        pb.Algorithm.PROPORTIONAL_SHARE,
        [("c0", 0, 60, 60, 1), ("c1", 0, 75, 60, 1), ("c2", 0, 10, 0, 1)],
        120,
        preload=False,
        variant="topup",
    )


def test_proportional_topup_multiple_subclients():
    run_cases(
        pb.Algorithm.PROPORTIONAL_SHARE,
        [
            ("c0", 0, 65, 60, 3),
            ("c1", 0, 45, 40, 2),
            ("c2", 0, 20, 20, 1),
        ],
        120,
        variant="topup",
    )
    run_cases(
        pb.Algorithm.PROPORTIONAL_SHARE,
        [
            ("c0", 0, 65, 65, 3),
            ("c1", 0, 45, 45, 2),
            ("c2", 0, 20, 10, 1),
        ],
        120,
        preload=False,
        variant="topup",
    )


def test_proportional_share_sim_semantics():
    # Canonical PROPORTIONAL_SHARE follows the simulation formula: overload
    # scales everyone by capacity / all_wants (clamped by the free capacity,
    # which for the last client is within rounding of its scaled wants).
    p = 120.0 / 130.0
    store = LeaseStore("test")
    algo = make_algo(pb.Algorithm.PROPORTIONAL_SHARE)
    for c, w in [("c0", 60.0), ("c1", 60.0), ("c2", 10.0)]:
        store.assign(c, 300, 5, 0.0, w, 1)
    for c, w in [("c0", 60.0), ("c1", 60.0), ("c2", 10.0)]:
        lease = algo(store, 120.0, Request(c, 0.0, w, 1))
        assert lease.has == pytest.approx(w * p)
        assert store.sum_has <= 120.0 + 1e-9
    # Underload: everyone gets wants.
    run_cases(
        pb.Algorithm.PROPORTIONAL_SHARE,
        [("c0", 0, 30, 30, 1), ("c1", 0, 40, 40, 1)],
        120,
    )


def test_learn_grants_reported_has():
    from doorman_tpu.algorithms import learn

    store = LeaseStore("test")
    algo = learn(pb.Algorithm(lease_length=60, refresh_interval=16))
    lease = algo(store, 100, Request("a", has=33.0, wants=50.0, subclients=1))
    assert lease.has == 33.0
    assert lease.wants == 50.0


def test_lease_length_and_refresh_interval():
    import time

    store = LeaseStore("test")
    algo = make_algo(pb.Algorithm.PROPORTIONAL_SHARE, lease=342, refresh=5)
    now = time.time()
    lease = algo(store, 100, Request("b", 0, 10, 1))
    assert abs((lease.expiry - now) - 342) <= 1
    assert lease.refresh_interval == 5


@pytest.mark.parametrize(
    "kind",
    [
        pb.Algorithm.NO_ALGORITHM,
        pb.Algorithm.STATIC,
        pb.Algorithm.PROPORTIONAL_SHARE,
        pb.Algorithm.FAIR_SHARE,
    ],
)
def test_registry_covers_all_kinds(kind):
    assert get_algorithm(
        pb.Algorithm(kind=kind, lease_length=60, refresh_interval=16)
    ) is not None
