"""solve_chunked (the wide-resource two-level layout) vs solve_dense
and the numpy oracles. When every resource is exactly one chunk the two
layouts must agree BYTE-identically (segment_sum over singleton sorted
segments adds nothing); multi-chunk resources are held to the oracle
within float-reassociation tolerance."""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

import jax

from doorman_tpu.algorithms.tick import oracle_row
from doorman_tpu.solver.dense import (
    ChunkedDenseBatch,
    DenseBatch,
    solve_chunked_jit,
    solve_dense_jit,
)


def random_dense(rng, R=16, K=8):
    n = rng.integers(1, K + 1, R)
    act = np.arange(K)[None, :] < n[:, None]
    wants = rng.random((R, K)) * 100 * act
    has = rng.random((R, K)) * 50 * act
    sub = rng.integers(1, 4, (R, K)) * act
    cap = rng.random(R) * 400 + 10
    kind = rng.choice(np.array([0, 1, 2, 3, 4], np.int32), R)
    statc = rng.random(R) * 40
    learning = np.zeros(R, bool)
    return wants, has, sub, act, cap, kind, learning, statc


def test_single_chunk_matches_dense_exactly():
    rng = np.random.default_rng(5)
    wants, has, sub, act, cap, kind, learning, statc = random_dense(rng)
    dense = DenseBatch(
        wants=wants, has=has, subclients=sub.astype(float), active=act,
        capacity=cap, algo_kind=kind, learning=learning,
        static_capacity=statc,
    )
    chunked = ChunkedDenseBatch(
        wants=wants, has=has, subclients=sub.astype(float), active=act,
        row_seg=np.arange(16, dtype=np.int32),
        capacity=cap, algo_kind=kind, learning=learning,
        static_capacity=statc,
    )
    a = np.asarray(solve_dense_jit(dense))
    b = np.asarray(solve_chunked_jit(chunked))
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("kind", [0, 1, 2, 3, 4])
def test_multi_chunk_matches_oracle(kind):
    """One resource of 37 clients split over 5 chunk rows of width 8,
    plus a padding row mapped to a padding segment."""
    rng = np.random.default_rng(kind + 10)
    n, K = 37, 8
    R = 6  # 5 data rows + 1 padding row
    wants_f = rng.random(n) * 100
    has_f = rng.random(n) * 50
    sub_f = rng.integers(1, 4, n).astype(float)
    cap = 600.0
    statc = 30.0

    wants = np.zeros((R, K))
    has = np.zeros((R, K))
    sub = np.zeros((R, K))
    act = np.zeros((R, K), bool)
    rows = np.arange(n) // K
    lanes = np.arange(n) % K
    wants[rows, lanes] = wants_f
    has[rows, lanes] = has_f
    sub[rows, lanes] = sub_f
    act[rows, lanes] = True
    row_seg = np.array([0, 0, 0, 0, 0, 1], np.int32)
    batch = ChunkedDenseBatch(
        wants=wants, has=has, subclients=sub, active=act, row_seg=row_seg,
        capacity=np.array([cap, 0.0]),
        algo_kind=np.array([kind, 0], np.int32),
        learning=np.zeros(2, bool),
        static_capacity=np.array([statc, 0.0]),
    )
    gets = np.asarray(solve_chunked_jit(batch))
    expected = oracle_row(kind, cap, statc, wants_f, has_f, sub_f)
    np.testing.assert_allclose(
        gets[rows, lanes], expected, rtol=1e-9, atol=1e-12
    )
    # Padding row and inactive lanes produce zeros.
    assert (gets[5] == 0).all()
    assert gets[4, 5:].sum() == 0


def test_learning_segment_replays_has():
    rng = np.random.default_rng(2)
    wants, has, sub, act, cap, kind, _, statc = random_dense(rng, R=4)
    learning = np.array([True, False, True, False])
    batch = ChunkedDenseBatch(
        wants=wants, has=has, subclients=sub.astype(float), active=act,
        row_seg=np.arange(4, dtype=np.int32), capacity=cap,
        algo_kind=kind, learning=learning, static_capacity=statc,
    )
    gets = np.asarray(solve_chunked_jit(batch))
    np.testing.assert_array_equal(gets[0], has[0] * act[0])
    np.testing.assert_array_equal(gets[2], has[2] * act[2])
