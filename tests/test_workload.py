"""Workload harness: scenario determinism, SLO gate verdicts, the
generators' load arcs, and the predictive-admission head-to-head.

The load-bearing pin is byte-stable replay: the same spec + seed must
reproduce the event log byte-for-byte (log_sha256 equality), because
the scenario library's bench rows use that digest as the replay
contract. The second pin is the predictive pair: the forecaster-fed
controller must hold the top band at least as well as the reactive
controller through the later flash-crowd cycles — the paper-side claim
the flash_crowd_predictive scenario exists to keep honest.
"""

import asyncio
import json

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.obs import slo as slo_mod
from doorman_tpu.workload.harness import WorkloadRunner
from doorman_tpu.workload.scenarios import (
    SCENARIOS,
    run_scenario,
    scenario_lines,
)
from doorman_tpu.workload.spec import GeneratorSpec, WorkloadSpec


def run(coro):
    return asyncio.run(coro)


def _small_flash_crowd(seed=0):
    return WorkloadSpec.make(
        "t_flash", 16, seed=seed, capacity=100.0,
        algorithm="PRIORITY_BANDS",
        admission={"max_rps": 10.0},
        base_clients=[(1, 10.0)] * 3,
        generators=[
            GeneratorSpec.make(
                "flash_crowd", at=4, duration=4, clients=10, band=0,
                wants=10.0,
            ),
        ],
        gates={"top_band_satisfaction": 0.9},
    )


# ----------------------------------------------------------------------
# Determinism
# ----------------------------------------------------------------------


def test_event_log_replays_byte_identically():
    a = run(WorkloadRunner(_small_flash_crowd()).run())
    b = run(WorkloadRunner(_small_flash_crowd()).run())
    assert a["event_log"] == b["event_log"]
    assert a["log_sha256"] == b["log_sha256"]
    # And the digest really is over the canonical log bytes.
    import hashlib

    payload = json.dumps(
        a["event_log"], sort_keys=True, separators=(",", ":")
    ).encode()
    assert hashlib.sha256(payload).hexdigest() == a["log_sha256"]


def test_different_seed_diverges():
    a = run(WorkloadRunner(_small_flash_crowd(seed=0)).run())
    b = run(WorkloadRunner(_small_flash_crowd(seed=7)).run())
    # Admission shed draws come from the seed; the logs must differ.
    assert a["log_sha256"] != b["log_sha256"]


def test_spec_round_trips_through_json():
    spec = SCENARIOS["flash_crowd_predictive"]()
    clone = WorkloadSpec.from_dict(
        json.loads(json.dumps(spec.as_dict()))
    )
    assert clone == spec


# ----------------------------------------------------------------------
# Scenario library verdicts
# ----------------------------------------------------------------------


def test_scenario_registry_has_the_named_scenarios():
    for name in ("diurnal", "flash_crowd", "rolling_deploy",
                 "multi_region", "elastic_preempt"):
        assert name in SCENARIOS
    lines = dict(scenario_lines())
    assert all(doc for doc in lines.values()), lines


def test_unknown_scenario_and_unknown_gate_raise():
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("no_such_scenario")
    with pytest.raises(ValueError, match="unknown workload gate"):
        slo_mod.workload_slos({"bogus_gate": 1.0}, name_prefix="x")


def test_rolling_deploy_hands_over_and_reconverges():
    v = run_scenario("rolling_deploy", seed=0)
    assert v["ok"], v["slo"]
    assert v["summary"]["master_changes"] >= 3
    assert v["summary"]["reconverge_ticks"] <= 6
    # The handover arc is in the event log: a deploy entry per server
    # and a master-set change following each.
    kinds = [row[1] for row in v["event_log"]]
    assert kinds.count("deploy") == 2
    assert kinds.count("master") >= 3


def test_elastic_jobs_preempt_and_still_complete():
    v = run_scenario("elastic_preempt", seed=0)
    assert v["ok"], v["slo"]
    assert v["summary"]["preemptions"] >= 1
    assert v["summary"]["completions"] == 6.0
    kinds = [row[1] for row in v["event_log"]]
    assert "elastic_preempt" in kinds and "elastic_complete" in kinds
    # Preempted jobs requeue before completing.
    assert "elastic_requeue" in kinds


def test_federated_crowd_holds_the_capacity_sum():
    v = run_scenario("flash_crowd_federated", seed=0)
    assert v["ok"], v["slo"]
    assert v["summary"]["fed_capacity_violations"] == 0.0
    assert any(row[1] == "straddle" for row in v["event_log"])


def test_flash_crowd_gates_and_flightrec_dump_on_failure():
    v = run_scenario("flash_crowd", seed=0)
    assert v["ok"], v["slo"]
    slos = {x["slo"]: x for x in v["slo"]["verdicts"]}
    assert slos["workload:flash_crowd:top_band_goodput"][
        "status"
    ] == "pass"
    assert v["flightrec_dump"] is None
    # An unreachable gate fails the run and triggers the black-box
    # dump, carrying the per-tick beat for triage.
    spec = _small_flash_crowd().with_(
        gates={"top_band_satisfaction": 2.0}
    )
    bad = run(WorkloadRunner(spec).run())
    assert not bad["ok"]
    assert bad["flightrec_dump"] is not None
    assert bad["flightrec_dump"]["records"], bad["flightrec_dump"]


def test_pooled_streaming_scenario_carries_traffic_and_replays():
    v = run_scenario("diurnal_streaming_pooled", scale=0.5, seed=3,
                     ticks=18)
    assert v["ok"], v["slo"]
    # The serving plane visibly carried the stream traffic: the pool
    # pumped ring frames AND still holds every stream at run end (a
    # silent fall-back to the in-process path would zero both).
    assert v["summary"]["frontend_frames"] > 0
    assert v["summary"]["frontend_held"] == 2.0
    assert v["summary"]["stream_pushes"] > 0
    fe = v["frontend"]["s0"]
    assert fe["live"] == [0, 1] and fe["crashes"] == 0
    # No pump anomalies on a healthy run: laps/corrupt frames would
    # get their own frontend_pump log entry.
    assert not any(row[1] == "frontend_pump" for row in v["event_log"])
    # Byte-stable replay holds with the pool in the loop.
    w = run_scenario("diurnal_streaming_pooled", scale=0.5, seed=3,
                     ticks=18)
    assert w["log_sha256"] == v["log_sha256"]


# ----------------------------------------------------------------------
# Predictive head-to-head
# ----------------------------------------------------------------------


def test_predictive_beats_reactive_on_the_repeating_crowd():
    v = run_scenario("flash_crowd_predictive", seed=0)
    assert v["ok"], v["slo"]
    slos = {x["slo"]: x for x in v["slo"]["verdicts"]}
    pair = slos[
        "workload:flash_crowd_predictive:predictive_over_reactive"
    ]
    assert pair["status"] == "pass", pair
    # Not merely "no worse": the forecaster-primed controller must
    # strictly improve the stressed top band on this scenario.
    assert pair["detail"]["predictive"] > pair["detail"]["reactive"]
    # The forecast reached the controller (logged when it moves).
    assert any(row[1] == "forecast" for row in v["event_log"])
    assert v["summary"]["forecaster"]["ticks_observed"] == v["ticks"]


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------


def test_workload_cli_list_and_verdict(tmp_path, capsys):
    from doorman_tpu.cmd import workload as cli

    assert cli.run(cli.make_parser().parse_args(
        ["--list-scenarios"]
    )) == 0
    listed = capsys.readouterr().out
    for name in SCENARIOS:
        assert name in listed

    out = tmp_path / "verdict.json"
    rc = cli.run(cli.make_parser().parse_args([
        "--scenario", "rolling_deploy", "--out", str(out),
    ]))
    assert rc == 0
    v = json.loads(out.read_text())
    assert v["scenario"] == "rolling_deploy" and v["ok"]


def test_sim_cli_lists_scenarios(capsys):
    import sys
    from unittest import mock

    from doorman_tpu.sim.__main__ import main as sim_main

    with mock.patch.object(
        sys, "argv", ["sim", "--list-scenarios"]
    ):
        sim_main()
    out = capsys.readouterr().out
    assert "1_maxmin" in out and "Convergence" in out
