"""Lease store tests (capability parity with reference store_test.go, but on
an injected virtual clock instead of real 10s sleeps)."""

from doorman_tpu.core import LeaseStore


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_assign_updates_sums():
    s = LeaseStore("r")
    s.assign("a", 300, 5, has=10, wants=20, subclients=1)
    s.assign("b", 300, 5, has=5, wants=7, subclients=2)
    assert s.sum_has == 15
    assert s.sum_wants == 27
    assert s.count == 3
    assert len(s) == 2


def test_reassign_applies_delta():
    s = LeaseStore("r")
    s.assign("a", 300, 5, has=10, wants=20, subclients=1)
    s.assign("a", 300, 5, has=4, wants=6, subclients=3)
    assert s.sum_has == 4
    assert s.sum_wants == 6
    assert s.count == 3
    assert len(s) == 1


def test_release():
    s = LeaseStore("r")
    s.assign("a", 300, 5, has=10, wants=20, subclients=1)
    s.assign("b", 300, 5, has=1, wants=2, subclients=1)
    s.release("a")
    assert s.sum_has == 1
    assert s.sum_wants == 2
    assert s.count == 1
    assert not s.has_client("a")
    s.release("missing")  # no-op
    assert s.count == 1


def test_get_missing_is_zero_lease():
    s = LeaseStore("r")
    lease = s.get("nope")
    assert lease.is_zero
    assert lease.has == 0.0
    assert s.subclients("nope") == 0


def test_clean_expired():
    clock = FakeClock()
    s = LeaseStore("r", clock=clock)
    s.assign("short", lease_length=5, refresh_interval=1, has=1, wants=1, subclients=1)
    s.assign("long", lease_length=50, refresh_interval=1, has=2, wants=2, subclients=1)
    clock.advance(10)
    assert s.clean() == 1
    assert not s.has_client("short")
    assert s.has_client("long")
    assert s.sum_has == 2


def test_lease_status_snapshot():
    s = LeaseStore("r")
    s.assign("a", 300, 5, has=10, wants=20, subclients=1)
    st = s.lease_status()
    assert st.id == "r"
    assert st.sum_has == 10
    assert st.sum_wants == 20
    assert len(st.leases) == 1
    assert st.leases[0].client_id == "a"
