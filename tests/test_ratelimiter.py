"""Rate limiter tests with a hand-fed fake resource (capability parity with
reference ratelimiter_test.go:26-190): blocked at capacity 0, ~100ms waits
at capacity 10, unlimited at capacity -1, and the adaptive wants
estimator."""

import asyncio
import time

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.ratelimiter import new_qps
from doorman_tpu.ratelimiter.adaptive import wants_estimate


class FakeResource:
    """Implements the ClientResource surface the limiter needs, with a
    hand-fed capacity queue (mirrors the reference's fakeResource)."""

    def __init__(self):
        self._capacity = asyncio.Queue(maxsize=32)
        self.asked = []

    def capacity(self):
        return self._capacity

    async def ask(self, wants):
        self.asked.append(wants)

    async def feed(self, value):
        await self._capacity.put(value)


def run(coro):
    return asyncio.run(coro)


def test_blocked_at_zero_capacity():
    async def body():
        res = FakeResource()
        rl = new_qps(res)
        await res.feed(0.0)
        await asyncio.sleep(0.05)
        with pytest.raises(asyncio.TimeoutError):
            await rl.wait(timeout=0.2)
        await rl.close()

    run(body())


def test_unlimited_never_blocks():
    async def body():
        res = FakeResource()
        rl = new_qps(res)
        await res.feed(-1.0)
        await asyncio.sleep(0.05)
        start = time.monotonic()
        for _ in range(100):
            await rl.wait(timeout=1)
        assert time.monotonic() - start < 0.5
        await rl.close()

    run(body())


def test_capacity_10_paces_to_100ms():
    async def body():
        res = FakeResource()
        rl = new_qps(res)
        await res.feed(10.0)
        await asyncio.sleep(0.05)
        start = time.monotonic()
        n = 4
        for _ in range(n):
            await rl.wait(timeout=5)
        elapsed = time.monotonic() - start
        # ~100ms per permit (first may come within the first subinterval).
        assert 0.15 <= elapsed <= 1.5
        await rl.close()

    run(body())


def test_capacity_update_unblocks_waiters():
    async def body():
        res = FakeResource()
        rl = new_qps(res)
        await res.feed(0.0)
        await asyncio.sleep(0.05)

        async def release_later():
            await asyncio.sleep(0.1)
            await res.feed(-1.0)

        task = asyncio.create_task(release_later())
        await rl.wait(timeout=2)
        await task
        await rl.close()

    run(body())


def test_budget_does_not_accumulate():
    async def body():
        res = FakeResource()
        rl = new_qps(res)
        await res.feed(5.0)  # one permit per 200ms
        # Sleep 1s without consuming: budget must not pile up.
        await asyncio.sleep(1.0)
        start = time.monotonic()
        # 5 waits need >= 4 timer ticks (>= 0.6s): an accumulated burst
        # would finish almost instantly.
        for _ in range(5):
            await rl.wait(timeout=5)
        elapsed = time.monotonic() - start
        assert elapsed >= 0.55
        await rl.close()

    run(body())


def test_capacity_drop_discards_stale_budget():
    async def body():
        res = FakeResource()
        rl = new_qps(res)
        await res.feed(1000.0)  # large per-subinterval budget
        await asyncio.sleep(0.15)  # budget accrues
        await res.feed(0.0)  # capacity revoked
        await asyncio.sleep(0.05)
        # No stale permits may leak through after the revocation.
        with pytest.raises(asyncio.TimeoutError):
            await rl.wait(timeout=0.2)
        await rl.close()

    run(body())


def test_wants_estimate_recency_weighting():
    now = 1000.0
    # 10 calls in the most recent second: weighted sum = 10*10=100,
    # normalizer k(k+1)/2 = 55.
    entries = [now - 0.5] * 10
    assert wants_estimate(entries, 10.0, now) == pytest.approx(100 / 55)
    # Old entries outside the window are ignored.
    entries = [now - 20.0] * 10
    assert wants_estimate(entries, 10.0, now) == 0.0
