"""Seasonal forecaster: device/host bit-identity and the invariants
the predictive-admission seam leans on.

The bit-identity pin follows the repo's parity convention (see
tests/test_fairness_lanes.py): the update is written in delta form
with power-of-two gains, so every multiply is exact in float32 and
XLA's FMA fusion rounds identically to numpy's separate ops — the
device path must reproduce the numpy host oracle BIT-FOR-BIT, not
approximately. The envelope invariant (forecasts clipped to the
observed range) is what lets the admission controller trust an
arbitrary forecast: a diverging season term can never demand a shed
harder than the worst tick actually seen.
"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.workload import forecast as fc

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - optional dependency
    HAVE_HYPOTHESIS = False


def _run_pair(series, period, ticks, seed, alpha=0.5, beta=0.25):
    rng = np.random.default_rng(seed)
    xs = rng.uniform(0.0, 100.0, (ticks, series)).astype(np.float32)
    host = fc.SeasonalForecaster(
        series=series, period=period, alpha=alpha, beta=beta,
        engine="host",
    )
    dev = fc.SeasonalForecaster(
        series=series, period=period, alpha=alpha, beta=beta,
        engine="device",
    )
    return xs, host, dev


def test_device_path_is_bit_identical_to_host_oracle():
    if not fc.device_available():
        pytest.skip("no jax device path")
    xs, host, dev = _run_pair(series=4, period=8, ticks=300, seed=42)
    for t in range(xs.shape[0]):
        h = host.observe(xs[t])
        d = dev.observe(xs[t])
        assert h.dtype == np.float32 and d.dtype == np.float32
        np.testing.assert_array_equal(
            h.view(np.uint32), d.view(np.uint32),
            err_msg=f"bit divergence at tick {t}",
        )


def test_constant_traffic_is_an_exact_fixpoint():
    f = fc.SeasonalForecaster(series=2, period=4, engine="host")
    x = np.asarray([7.0, 0.0], np.float32)
    for _ in range(40):
        out = f.observe(x)
    # Delta-form updates leave a constant series untouched: the level
    # IS the rate, the season is exactly zero, forecast == rate.
    np.testing.assert_array_equal(out, x)


def test_forecast_stays_inside_the_observed_envelope():
    rng = np.random.default_rng(3)
    f = fc.SeasonalForecaster(series=3, period=5, engine="host")
    lo = np.full(3, np.inf, np.float32)
    hi = np.full(3, -np.inf, np.float32)
    for _ in range(200):
        x = rng.uniform(-50.0, 50.0, 3).astype(np.float32)
        lo, hi = np.minimum(lo, x), np.maximum(hi, x)
        out = f.observe(x)
        assert (out >= lo).all() and (out <= hi).all()


def test_non_dyadic_gains_are_rejected():
    # The bit-parity convention requires power-of-two gains; anything
    # else reintroduces FMA-sensitive rounding.
    with pytest.raises(ValueError, match="power of two"):
        fc.SeasonalForecaster(series=1, period=4, alpha=0.3)
    with pytest.raises(ValueError, match="power of two"):
        fc.SeasonalForecaster(series=1, period=4, beta=0.75)
    fc.SeasonalForecaster(series=1, period=4, alpha=0.125, beta=1.0)


def test_status_and_tick_accounting():
    f = fc.SeasonalForecaster(series=2, period=4, engine="host")
    for t in range(9):
        f.observe(np.asarray([float(t), 1.0], np.float32))
    s = f.status()
    assert s["ticks_observed"] == 9 and s["period"] == 4
    assert s["engine"] == "host" and s["seen"]


if HAVE_HYPOTHESIS:

    @settings(max_examples=50, deadline=None)
    @given(
        xs=st.lists(
            st.floats(
                min_value=0.0, max_value=1e6, allow_nan=False,
                allow_infinity=False, width=32,
            ),
            min_size=1, max_size=60,
        ),
        period=st.integers(min_value=1, max_value=12),
    )
    def test_envelope_invariant_holds_for_any_stream(xs, period):
        f = fc.SeasonalForecaster(series=1, period=period,
                                  engine="host")
        seen = []
        for x in xs:
            seen.append(np.float32(x))
            out = f.observe(np.asarray([x], np.float32))
            assert min(seen) <= out[0] <= max(seen)

    @settings(max_examples=50, deadline=None)
    @given(
        x=st.floats(
            min_value=0.0, max_value=1e6, allow_nan=False,
            allow_infinity=False, width=32,
        ),
        period=st.integers(min_value=1, max_value=8),
        ticks=st.integers(min_value=1, max_value=40),
    )
    def test_constant_fixpoint_holds_for_any_rate(x, period, ticks):
        f = fc.SeasonalForecaster(series=1, period=period,
                                  engine="host")
        arr = np.asarray([x], np.float32)
        out = arr
        for _ in range(ticks):
            out = f.observe(arr)
        np.testing.assert_array_equal(out, arr)


# ----------------------------------------------------------------------
# warm_start: restart-spanning bit identity
# ----------------------------------------------------------------------


def test_warm_start_is_bit_identical_to_online_feeding():
    """Replaying a history through warm_start IS observe: the primed
    model's state and next forecast match a never-restarted twin to
    the bit."""
    from doorman_tpu.obs.history import HistoryStore

    hist = HistoryStore(ring=64, clock=lambda: 0.0)
    offered = [0.0, 3.0, 17.0, 4.0, 9.0, 9.0, 2.0, 30.0]
    for i, v in enumerate(offered):
        hist.append({"tick": i, "offered": v})

    warm = fc.SeasonalForecaster(series=2, period=4, engine="host")
    fed = warm.warm_start(hist, interval=2.0)
    assert fed == len(offered)
    assert warm.ticks_observed == len(offered)

    live = fc.SeasonalForecaster(series=2, period=4, engine="host")
    for v in offered:
        live.observe(np.full(2, np.float32(v / 2.0), np.float32))

    for w, l in zip(warm._state, live._state):
        np.testing.assert_array_equal(
            np.asarray(w, np.float32).view(np.uint32),
            np.asarray(l, np.float32).view(np.uint32),
        )
    nxt = np.asarray([5.0, 6.0], np.float32)
    np.testing.assert_array_equal(
        warm.observe(nxt).view(np.uint32),
        live.observe(nxt).view(np.uint32),
    )


def test_warm_start_accepts_scalars_and_skips_missing_fields():
    f = fc.SeasonalForecaster(series=1, period=2, engine="host")
    fed = f.warm_start([1.0, {"offered": 2.0}, {"other": 9.0}, 3.0])
    assert fed == 3  # the field-less dict is skipped, not an error
    assert f.ticks_observed == 3


def test_runner_takes_a_primed_forecaster():
    """A history-primed forecaster rides the workload harness: the
    runner uses it as-is, so its ticks_observed span the prior run
    plus this one."""
    import asyncio

    from doorman_tpu.workload.harness import WorkloadRunner
    from doorman_tpu.workload.spec import WorkloadSpec

    def spec(seed=0):
        return WorkloadSpec.make(
            "t_warm", 12, seed=seed, capacity=100.0,
            algorithm="PRIORITY_BANDS",
            admission={"max_rps": 10.0},
            base_clients=[(0, 10.0), (1, 10.0), (1, 10.0)],
            predictive={"period": 4, "alpha": 0.25, "beta": 0.5},
        )

    preset = fc.SeasonalForecaster(
        series=2, period=4, alpha=0.25, beta=0.5, engine="host"
    )
    warm_ticks = preset.warm_start([4.0] * 8)
    runner = WorkloadRunner(spec(), forecaster=preset)
    v = asyncio.run(runner.run())
    assert runner.forecaster is preset
    assert v["summary"]["forecaster"]["ticks_observed"] == (
        warm_ticks + v["ticks"]
    )

    # A preset whose series count disagrees with the predictive
    # config's bands is a config error at construction — before run()
    # has started anything a failure would leak.
    wrong = fc.SeasonalForecaster(series=3, period=4, engine="host")
    with pytest.raises(ValueError, match="series"):
        WorkloadRunner(spec(), forecaster=wrong)
