"""Seasonal forecaster: device/host bit-identity and the invariants
the predictive-admission seam leans on.

The bit-identity pin follows the repo's parity convention (see
tests/test_fairness_lanes.py): the update is written in delta form
with power-of-two gains, so every multiply is exact in float32 and
XLA's FMA fusion rounds identically to numpy's separate ops — the
device path must reproduce the numpy host oracle BIT-FOR-BIT, not
approximately. The envelope invariant (forecasts clipped to the
observed range) is what lets the admission controller trust an
arbitrary forecast: a diverging season term can never demand a shed
harder than the worst tick actually seen.
"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.workload import forecast as fc

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - optional dependency
    HAVE_HYPOTHESIS = False


def _run_pair(series, period, ticks, seed, alpha=0.5, beta=0.25):
    rng = np.random.default_rng(seed)
    xs = rng.uniform(0.0, 100.0, (ticks, series)).astype(np.float32)
    host = fc.SeasonalForecaster(
        series=series, period=period, alpha=alpha, beta=beta,
        engine="host",
    )
    dev = fc.SeasonalForecaster(
        series=series, period=period, alpha=alpha, beta=beta,
        engine="device",
    )
    return xs, host, dev


def test_device_path_is_bit_identical_to_host_oracle():
    if not fc.device_available():
        pytest.skip("no jax device path")
    xs, host, dev = _run_pair(series=4, period=8, ticks=300, seed=42)
    for t in range(xs.shape[0]):
        h = host.observe(xs[t])
        d = dev.observe(xs[t])
        assert h.dtype == np.float32 and d.dtype == np.float32
        np.testing.assert_array_equal(
            h.view(np.uint32), d.view(np.uint32),
            err_msg=f"bit divergence at tick {t}",
        )


def test_constant_traffic_is_an_exact_fixpoint():
    f = fc.SeasonalForecaster(series=2, period=4, engine="host")
    x = np.asarray([7.0, 0.0], np.float32)
    for _ in range(40):
        out = f.observe(x)
    # Delta-form updates leave a constant series untouched: the level
    # IS the rate, the season is exactly zero, forecast == rate.
    np.testing.assert_array_equal(out, x)


def test_forecast_stays_inside_the_observed_envelope():
    rng = np.random.default_rng(3)
    f = fc.SeasonalForecaster(series=3, period=5, engine="host")
    lo = np.full(3, np.inf, np.float32)
    hi = np.full(3, -np.inf, np.float32)
    for _ in range(200):
        x = rng.uniform(-50.0, 50.0, 3).astype(np.float32)
        lo, hi = np.minimum(lo, x), np.maximum(hi, x)
        out = f.observe(x)
        assert (out >= lo).all() and (out <= hi).all()


def test_non_dyadic_gains_are_rejected():
    # The bit-parity convention requires power-of-two gains; anything
    # else reintroduces FMA-sensitive rounding.
    with pytest.raises(ValueError, match="power of two"):
        fc.SeasonalForecaster(series=1, period=4, alpha=0.3)
    with pytest.raises(ValueError, match="power of two"):
        fc.SeasonalForecaster(series=1, period=4, beta=0.75)
    fc.SeasonalForecaster(series=1, period=4, alpha=0.125, beta=1.0)


def test_status_and_tick_accounting():
    f = fc.SeasonalForecaster(series=2, period=4, engine="host")
    for t in range(9):
        f.observe(np.asarray([float(t), 1.0], np.float32))
    s = f.status()
    assert s["ticks_observed"] == 9 and s["period"] == 4
    assert s["engine"] == "host" and s["seen"]


if HAVE_HYPOTHESIS:

    @settings(max_examples=50, deadline=None)
    @given(
        xs=st.lists(
            st.floats(
                min_value=0.0, max_value=1e6, allow_nan=False,
                allow_infinity=False, width=32,
            ),
            min_size=1, max_size=60,
        ),
        period=st.integers(min_value=1, max_value=12),
    )
    def test_envelope_invariant_holds_for_any_stream(xs, period):
        f = fc.SeasonalForecaster(series=1, period=period,
                                  engine="host")
        seen = []
        for x in xs:
            seen.append(np.float32(x))
            out = f.observe(np.asarray([x], np.float32))
            assert min(seen) <= out[0] <= max(seen)

    @settings(max_examples=50, deadline=None)
    @given(
        x=st.floats(
            min_value=0.0, max_value=1e6, allow_nan=False,
            allow_infinity=False, width=32,
        ),
        period=st.integers(min_value=1, max_value=8),
        ticks=st.integers(min_value=1, max_value=40),
    )
    def test_constant_fixpoint_holds_for_any_rate(x, period, ticks):
        f = fc.SeasonalForecaster(series=1, period=period,
                                  engine="host")
        arr = np.asarray([x], np.float32)
        out = arr
        for _ in range(ticks):
            out = f.observe(arr)
        np.testing.assert_array_equal(out, arr)
