"""CLI and config-source tests: the server binary's serve() wiring (config
reload from file, debug pages), the one-shot client, the shell REPL
commands, and the SIGHUP-driven file source (capability parity with
reference configuration_test.go and the doorman_shell flow)."""

import asyncio
import os
import signal
import urllib.request

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.cmd import client as client_cmd
from doorman_tpu.cmd import server as server_cmd
from doorman_tpu.cmd.shell import Multiclient, eval_line
from doorman_tpu.server import sources

CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 90
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""

CONFIG_V2 = CONFIG.replace("90", "150")


def test_parse_source_rejects_garbage():
    with pytest.raises(ValueError):
        sources.parse_source("no-prefix")
    with pytest.raises(ValueError):
        sources.parse_source("zookeeper:/x")
    with pytest.raises(ValueError):
        sources.parse_source("etcd:/key", etcd_endpoints=[])


def test_local_file_sighup_reload(tmp_path):
    path = tmp_path / "config.yml"
    path.write_text("v1")

    async def body():
        source = sources.local_file(str(path))
        assert await asyncio.wait_for(source(), 5) == b"v1"
        path.write_text("v2")
        next_read = asyncio.create_task(source())
        await asyncio.sleep(0.05)
        assert not next_read.done()  # blocks until SIGHUP
        os.kill(os.getpid(), signal.SIGHUP)
        assert await asyncio.wait_for(next_read, 5) == b"v2"

    asyncio.run(body())


def test_two_local_file_sources_both_reload(tmp_path):
    """Two live file sources share the SIGHUP handler; one must not
    clobber the other."""
    a, b = tmp_path / "a.yml", tmp_path / "b.yml"
    a.write_text("a1")
    b.write_text("b1")

    async def body():
        src_a = sources.local_file(str(a))
        src_b = sources.local_file(str(b))
        assert await asyncio.wait_for(src_a(), 5) == b"a1"
        assert await asyncio.wait_for(src_b(), 5) == b"b1"
        a.write_text("a2")
        b.write_text("b2")
        next_a = asyncio.create_task(src_a())
        next_b = asyncio.create_task(src_b())
        await asyncio.sleep(0.05)
        os.kill(os.getpid(), signal.SIGHUP)
        assert await asyncio.wait_for(next_a, 5) == b"a2"
        assert await asyncio.wait_for(next_b, 5) == b"b2"

    asyncio.run(body())


def test_server_flag_parser_env_fallback(monkeypatch):
    monkeypatch.setenv("DOORMAN_PORT", "4242")
    parser = server_cmd.make_parser()
    from doorman_tpu.utils import flagenv

    flagenv.populate(parser)
    args = parser.parse_args([])
    assert args.port == 4242
    assert args.mode == "immediate"


async def _start_serve(args):
    """Run serve() as a task; returns (task, server, debug) once bound and
    configured."""
    started = asyncio.get_running_loop().create_future()
    task = asyncio.create_task(
        server_cmd.serve(args, on_started=lambda s, d: started.set_result((s, d)))
    )
    server, debug = await asyncio.wait_for(started, 10)
    await asyncio.wait_for(server.wait_until_configured(), 10)
    for _ in range(100):  # wait for the election callbacks to land
        if server.is_master:
            break
        await asyncio.sleep(0.05)
    return task, server, debug


async def _stop(task):
    task.cancel()
    try:
        await task
    except (asyncio.CancelledError, Exception):
        pass


def test_server_binary_end_to_end(tmp_path):
    """Start serve() with a file config, drive it with the one-shot client
    CLI and the shell, then reload config via SIGHUP."""
    config_path = tmp_path / "config.yml"
    config_path.write_text(CONFIG)

    async def body():
        parser = server_cmd.make_parser()
        args = parser.parse_args(
            [
                "--port", "0",
                "--host", "127.0.0.1",
                "--debug-port", "0",
                "--config", f"file:{config_path}",
                "--server-id", "cmd-test",
                "--minimum-refresh-interval", "0",
            ]
        )
        task, server, _ = await _start_serve(args)
        addr = f"127.0.0.1:{server.port}"
        server.current_master = addr

        # One-shot client.
        rc = await client_cmd.run(
            client_cmd.make_parser().parse_args(
                ["--server", addr, "--client-id", "oneshot", "r0", "30"]
            )
        )
        assert rc == 0

        # Shell flow.
        mc = Multiclient(addr)
        out = await eval_line(mc, "get alice r0 50")
        assert "alice: r0 = " in out
        out = await eval_line(mc, "get bob r0 60")
        assert "bob: r0 = " in out
        out = await eval_line(mc, "show all")
        assert "alice" in out and "bob" in out
        assert await eval_line(mc, "master")
        assert "unknown command" in (await eval_line(mc, "frobnicate"))
        out = await eval_line(mc, "release alice r0")
        assert "released" in out
        await mc.close()

        # SIGHUP config reload: capacity 90 -> 150.
        config_path.write_text(CONFIG_V2)
        os.kill(os.getpid(), signal.SIGHUP)
        for _ in range(100):
            await asyncio.sleep(0.05)
            res = server.resources.get("r0")
            if res is not None and res.capacity == 150:
                break
        else:
            raise AssertionError("config reload did not land")

        await _stop(task)

    asyncio.run(body())


def test_debug_port_serves_metrics(tmp_path):
    config_path = tmp_path / "config.yml"
    config_path.write_text(CONFIG)

    async def body():
        parser = server_cmd.make_parser()
        args = parser.parse_args(
            [
                "--port", "0",
                "--host", "127.0.0.1",
                "--debug-port", "0",
                "--config", f"file:{config_path}",
                "--server-id", "cmd-debug-test",
                "--trace",
            ]
        )
        task, _, debug = await _start_serve(args)
        assert debug is not None

        def fetch(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{debug.port}{path}", timeout=5
            ) as resp:
                return resp.read().decode()

        loop = asyncio.get_running_loop()
        text = await loop.run_in_executor(None, fetch, "/metrics")
        assert "doorman_server_is_master" in text
        # The per-serve registry re-exports the process-global default
        # registry (mastership transitions land there).
        assert "doorman_server_mastership_transitions" in text
        page = await loop.run_in_executor(None, fetch, "/debug/status")
        assert "cmd-debug-test" in page
        index = await loop.run_in_executor(None, fetch, "/debug")
        assert "/debug/traces" in index
        traces = await loop.run_in_executor(None, fetch, "/debug/traces")
        assert "tracer enabled" in traces

        await _stop(task)

    asyncio.run(body())
    from doorman_tpu.obs import trace as trace_mod

    trace_mod.default_tracer().disable()
    trace_mod.default_tracer().clear()

def test_server_jax_platform_flag_pins_backend(tmp_path):
    """--jax-platform spawns a real server process pinned to the named
    backend (the config knob, not the env var some plugin platforms
    ignore); /debug/status must report the pinned platform as the one
    actually solving — a grant alone would also pass if the flag were
    silently ignored."""
    import pathlib
    import re as _re
    import socket
    import subprocess
    import sys
    import time as _time
    import urllib.request

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    port, debug_port = free_port(), free_port()
    cfg = tmp_path / "cfg.yml"
    cfg.write_text(
        """
resources:
- identifier_glob: "*"
  capacity: 40
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 30,
              refresh_interval: 2, learning_mode_duration: 0}
"""
    )
    repo = pathlib.Path(__file__).resolve().parent.parent
    log = tmp_path / "server.log"
    with open(log, "w") as lf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "doorman_tpu.cmd.server",
             "--port", str(port), "--host", "127.0.0.1",
             "--debug-port", str(debug_port),
             "--mode", "batch", "--tick-interval", "0.3",
             "--jax-platform", "cpu",
             "--config", f"file:{cfg}",
             "--server-id", f"127.0.0.1:{port}"],
            cwd=repo, stdout=lf, stderr=subprocess.STDOUT, text=True,
        )
    try:
        deadline = _time.time() + 60
        out = None
        while _time.time() < deadline:
            assert proc.poll() is None, log.read_text()[-1500:]
            out = subprocess.run(
                [sys.executable, "-m", "doorman_tpu.cmd.client",
                 "--server", f"127.0.0.1:{port}", "--timeout", "10",
                 "res0", "5"],
                cwd=repo, capture_output=True, text=True, timeout=60,
            )
            if out.returncode == 0 and "got 5" in out.stdout:
                break
            _time.sleep(1)
        assert out is not None and "got 5" in out.stdout, (
            (out.stdout + out.stderr if out else "")
            + log.read_text()[-1500:]
        )
        # The platform that actually solved must be the pinned one
        # (reported only after the first tick completes — poll past the
        # first CPU compile).
        m = None
        deadline = _time.time() + 60
        while _time.time() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{debug_port}/debug/status", timeout=10
            ) as r:
                page = r.read().decode()
            m = _re.search(r"backend: ([a-z]+)", page)
            if m:
                break
            _time.sleep(1)
        assert m and m.group(1) == "cpu", (m and m.group(0), page[:500])
        # On a CPU-only host the backend reads "cpu" regardless; the
        # pin log line proves the flag was actually parsed and applied.
        assert "jax platform pinned to 'cpu'" in log.read_text()
    finally:
        proc.terminate()
        try:
            proc.wait(5)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


def test_chaos_cli_runs_plan(tmp_path):
    """The chaos entry point: `python -m doorman_tpu.cmd.chaos` lists
    plans as a real subprocess; the save -> load -> run flow executes a
    shipped plan from a JSON file and writes a passing verdict."""
    import json
    import pathlib
    import subprocess
    import sys

    from doorman_tpu.cmd import chaos as chaos_cmd

    repo = pathlib.Path(__file__).resolve().parent.parent
    out = subprocess.run(
        [sys.executable, "-m", "doorman_tpu.cmd.chaos", "--list"],
        cwd=repo, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0
    assert "master_flap" in out.stdout and "etcd_brownout" in out.stdout

    plan_path = tmp_path / "plan.json"
    verdict_path = tmp_path / "verdict.json"
    rc = asyncio.run(chaos_cmd.run(chaos_cmd.make_parser().parse_args(
        ["--save-plan", "etcd_brownout", str(plan_path)]
    )))
    assert rc == 0 and plan_path.exists()
    trace_path = tmp_path / "trace.json"
    rc = asyncio.run(chaos_cmd.run(chaos_cmd.make_parser().parse_args(
        ["--plan", str(plan_path), "--out", str(verdict_path),
         "--trace", str(trace_path)]
    )))
    assert rc == 0
    verdict = json.loads(verdict_path.read_text())
    assert verdict["plan"] == "etcd_brownout"
    assert verdict["ok"] and verdict["violations"] == []
    # --trace writes the run's virtual-time event log as a Chrome trace
    # (the same format obs.trace exports), loadable in Perfetto.
    trace = json.loads(trace_path.read_text())
    names = [e["name"] for e in trace["traceEvents"]]
    assert any(n.startswith("kv_drop") for n in names), names
