"""Device-resident tick solver vs the BatchSolver ground truth.

The resident path (solver/resident.py) keeps demand tables on device and
moves deltas; with rotate_ticks=1 (deliver every row every tick) and
sequential dispatch+collect it must produce byte-identical stores to the
full-reupload BatchSolver, tick for tick, through demand churn,
releases, new clients, expiry sweeps, and learning mode."""

import time

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.core.resource import Resource
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.solver.batch import BatchSolver
from doorman_tpu.solver.resident import ResidentDenseSolver

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

KINDS = [
    pb.Algorithm.NO_ALGORITHM,
    pb.Algorithm.STATIC,
    pb.Algorithm.PROPORTIONAL_SHARE,
    pb.Algorithm.FAIR_SHARE,
]


def make_world(clock, n_res=12, n_clients=9, seed=3):
    """One engine + resources with a deterministic population."""
    rng = np.random.default_rng(seed)
    engine = native.StoreEngine(clock=clock)
    resources = []
    for r in range(n_res):
        tpl = pb.ResourceTemplate(
            identifier_glob=f"res{r}",
            capacity=float(rng.integers(50, 500)),
            algorithm=pb.Algorithm(
                kind=int(KINDS[r % len(KINDS)]),
                lease_length=60,
                refresh_interval=5,
            ),
        )
        res = Resource(
            f"res{r}", tpl, clock=clock, store_factory=engine.store
        )
        resources.append(res)
        for c in range(n_clients):
            res.store.assign(
                f"c{r}_{c}", 60.0, 5.0, 0.0,
                float(rng.integers(1, 100)), 1,
            )
    return engine, resources


def all_leases(resources):
    out = {}
    for res in resources:
        for client, lease in res.store.items():
            out[(res.id, client)] = (
                lease.has, lease.wants, lease.subclients,
            )
    return out


def churn(resources, step, rng):
    """Deterministic mid-tick mutations shared by both worlds."""
    res = resources[step % len(resources)]
    # Change one client's wants.
    res.store.assign(
        f"c{resources.index(res)}_0", 60.0, 5.0,
        res.store.get(f"c{resources.index(res)}_0").has,
        float(rng.integers(1, 200)), 1,
    )
    if step % 3 == 1:
        res2 = resources[(step * 7) % len(resources)]
        i2 = resources.index(res2)
        res2.store.release(f"c{i2}_1")
    if step % 3 == 2:
        res3 = resources[(step * 5) % len(resources)]
        i3 = resources.index(res3)
        res3.store.assign(
            f"new{step}_{i3}", 60.0, 5.0, 0.0,
            float(rng.integers(1, 50)), 2,
        )


def test_resident_matches_batch_solver_tick_for_tick():
    t = [1000.0]
    clock = lambda: t[0]
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)

    resident = ResidentDenseSolver(
        eng_a, dtype=np.float64, clock=clock, rotate_ticks=1
    )
    batch = BatchSolver(dtype=np.float64, clock=clock)

    rng_a, rng_b = (np.random.default_rng(99) for _ in range(2))
    for step in range(8):
        churn(res_a, step, rng_a)
        churn(res_b, step, rng_b)
        if step == 4:
            # Learning mode flips on for one resource; the epoch bump
            # tells the resident solver to re-read templates (the server
            # bumps it on config reload / mastership change).
            res_a[2].learning_mode_end = t[0] + 100
            res_b[2].learning_mode_end = t[0] + 100
        resident.step(res_a, config_epoch=1 if step >= 4 else 0)
        batch.tick(res_b)
        a, b = all_leases(res_a), all_leases(res_b)
        assert a.keys() == b.keys(), f"membership diverged at tick {step}"
        for key in a:
            np.testing.assert_allclose(
                a[key], b[key], rtol=0, atol=0,
                err_msg=f"tick {step}, lease {key}",
            )
        t[0] += 1.0


def test_resident_rotation_converges_to_batch_fixpoint():
    """rotate_ticks>1 delivers each row every few ticks; with demand
    frozen, the stores must reach the same fixpoint as the batch path."""
    t = [500.0]
    clock = lambda: t[0]
    eng_a, res_a = make_world(clock, seed=11)
    eng_b, res_b = make_world(clock, seed=11)
    resident = ResidentDenseSolver(
        eng_a, dtype=np.float64, clock=clock, rotate_ticks=4
    )
    batch = BatchSolver(dtype=np.float64, clock=clock)
    for _ in range(12):
        resident.step(res_a)
        batch.tick(res_b)
        t[0] += 1.0
    a, b = all_leases(res_a), all_leases(res_b)
    assert a.keys() == b.keys()
    for key in a:
        np.testing.assert_allclose(a[key], b[key], err_msg=str(key))


def test_version_guard_skips_stale_rows():
    """A membership change between dispatch and collect must not write
    stale slot-ordered grants into the store."""
    t = [100.0]
    clock = lambda: t[0]
    engine, resources = make_world(clock, n_res=3, n_clients=4)
    resident = ResidentDenseSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1
    )
    resident.step(resources)  # settle
    handle = resident.dispatch(resources)
    # Membership changes mid-flight on resource 0.
    resources[0].store.release("c0_1")
    before = all_leases(resources)
    applied = resident.collect(handle)
    after = all_leases(resources)
    # Rows 1,2 (and the padding row is skipped in C): resource 0 skipped.
    assert applied == 2
    for (rid, client), lease in after.items():
        if rid == "res0":
            assert lease == before[(rid, client)], "stale row was applied"
    # The mid-flight change re-dirties the row; the next tick delivers.
    resident.step(resources)
    t[0] += 1.0
    resident.step(resources)
    assert resident.ticks >= 3


def make_prop_world(clock, n_res=12, n_clients=5, cap=1000.0, wants=400.0):
    """All-PROPORTIONAL_SHARE world, oversubscribed (5 x 400 > 1000)."""
    engine = native.StoreEngine(clock=clock)
    resources = []
    for r in range(n_res):
        tpl = pb.ResourceTemplate(
            identifier_glob=f"res{r}",
            capacity=cap,
            algorithm=pb.Algorithm(
                kind=pb.Algorithm.PROPORTIONAL_SHARE,
                lease_length=60,
                refresh_interval=5,
            ),
        )
        res = Resource(
            f"res{r}", tpl, clock=clock, store_factory=engine.store
        )
        resources.append(res)
        for c in range(n_clients):
            res.store.assign(f"c{r}_{c}", 60.0, 5.0, 0.0, wants, 1)
    return engine, resources


def test_capacity_cut_reaches_store_within_one_tick():
    """A config-epoch bump (capacity cut 1000 -> 100) must land in the
    store of record at the very next tick — NOT after the rotation
    cadence. Reference semantics: new config applies at the next decide
    (go/server/doorman/resource.go:117-140)."""
    t = [100.0]
    clock = lambda: t[0]
    engine, resources = make_prop_world(clock)
    solver = ResidentDenseSolver(
        engine, dtype=np.float64, clock=clock,
        rotate_ticks=10_000,  # rotation alone would take ~10k ticks
    )
    for _ in range(4):  # converge to the 1000-capacity steady state
        solver.step(resources)
        t[0] += 1.0
    for res in resources:
        assert res.store.sum_has == pytest.approx(1000.0)

    for res in resources:
        res.template.capacity = 100.0
    solver.step(resources, config_epoch=1)
    for res in resources:
        assert res.store.sum_has <= 100.0 + 1e-9, (
            f"{res.id}: store kept over-capacity grants after the cut"
        )


def test_parent_expiry_zeroes_store_same_tick_without_epoch_bump():
    """Time-driven config drift (a parent lease expiring between ticks)
    changes no epoch, but the affected row's zeroed grants must still be
    delivered that tick, not when rotation happens past it."""
    t = [100.0]
    clock = lambda: t[0]
    engine, resources = make_prop_world(clock, n_res=8)
    resources[3].parent_expiry = 110.0
    solver = ResidentDenseSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=10_000
    )
    for _ in range(3):
        solver.step(resources)
        t[0] += 1.0
    assert resources[3].store.sum_has == pytest.approx(1000.0)

    t[0] = 120.0  # past the parent expiry; epoch unchanged
    solver.step(resources)
    assert resources[3].store.sum_has == 0.0, (
        "expired-parent capacity cut did not reach the store same-tick"
    )
    # A row rotation hasn't reached keeps its pre-cut grants (delivery
    # was targeted, not a coincidental full pass).
    assert resources[6].store.sum_has == pytest.approx(1000.0)


def test_rotate_ticks_derived_from_refresh_cadence():
    """rotate_ticks=None derives rotation from min(refresh_interval) /
    tick_interval, so store staleness is bounded by the cadence clients
    actually refresh at; an explicit assignment pins it."""
    t = [100.0]
    clock = lambda: t[0]
    engine, resources = make_world(clock, n_res=4, n_clients=3)
    for res in resources:
        res.template.algorithm.refresh_interval = 16
    solver = ResidentDenseSolver(
        engine, dtype=np.float64, clock=clock,
        rotate_ticks=None, tick_interval=2.0,
    )
    solver.step(resources)
    assert solver.rotate_ticks == 8  # 16s refresh / 2s ticks

    # Faster refresh in the config tightens rotation on the epoch move.
    for res in resources:
        res.template.algorithm.refresh_interval = 6
    solver.step(resources, config_epoch=1)
    assert solver.rotate_ticks == 3

    solver.rotate_ticks = 5  # explicit pin wins from now on
    for res in resources:
        res.template.algorithm.refresh_interval = 40
    solver.step(resources, config_epoch=2)
    assert solver.rotate_ticks == 5


def test_server_capacity_cut_lands_next_tick_end_to_end():
    """Server-level: a config reload cutting capacity on a live
    batch+native (resident-path) server must reach both the store of
    record and the next client grant within a tick or two, not after
    the rotation cadence."""
    import asyncio

    import grpc

    from doorman_tpu.proto.grpc_api import CapacityStub
    from doorman_tpu.server.config import parse_yaml_config
    from doorman_tpu.server.election import TrivialElection
    from doorman_tpu.server.server import CapacityServer

    def config(cap):
        return parse_yaml_config(
            f"""
resources:
- identifier_glob: "shared"
  capacity: {cap}
  algorithm: {{kind: PROPORTIONAL_SHARE, lease_length: 60,
               refresh_interval: 30, learning_mode_duration: 0}}
- identifier_glob: "*"
  capacity: 500
  algorithm: {{kind: FAIR_SHARE, lease_length: 60, refresh_interval: 30,
               learning_mode_duration: 0}}
"""
        )

    async def body():
        server = CapacityServer(
            "cut", TrivialElection(), mode="batch", tick_interval=0.05,
            minimum_refresh_interval=0.0, native_store=True,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(config(1000))
        server.current_master = f"127.0.0.1:{port}"
        addr = f"127.0.0.1:{port}"

        def request(i):
            req = pb.GetCapacityRequest(client_id=f"c{i}")
            rr = req.resource.add()
            rr.resource_id = "shared"
            rr.wants = 200.0
            return req

        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)
            for i in range(20):  # 20 x 200 wants >> capacity
                await stub.GetCapacity(request(i))
            # Converge on the 1000-capacity allocation. The 30s
            # refresh_interval vs 0.05s ticks derives rotate_ticks=600,
            # capped at 64 — either way far beyond the couple of ticks
            # the cut below must land within.
            for _ in range(400):
                if (
                    server._resident is not None
                    and server._resident.ticks >= 4
                ):
                    break
                await asyncio.sleep(0.02)
            res = server.resources["shared"]
            assert res.store.sum_has == pytest.approx(1000.0, rel=1e-6)
            # Derived from 30s refresh / 0.05s ticks, capped at 64.
            assert server._resident.rotate_ticks == 64

            ticks_at_cut = server._resident.ticks
            await server.load_config(config(100))
            # One dispatch sees the new epoch; its collect lands one
            # pipelined tick later — "within a tick or two".
            for _ in range(400):
                if server._resident.ticks >= ticks_at_cut + 3:
                    break
                await asyncio.sleep(0.02)
            assert res.store.sum_has <= 100.0 + 1e-6, (
                f"store kept {res.store.sum_has} after the cut"
            )
            # And the next client grant is served from the cut store.
            out = await stub.GetCapacity(request(0))
            assert out.response[0].gets.capacity <= 100.0 + 1e-6
        await server.stop()

    asyncio.run(body())


def test_expiry_sweep_and_store_consistency():
    """Leases past expiry vanish on the next dispatch; engine aggregates
    stay consistent with per-lease state."""
    t = [100.0]
    clock = lambda: t[0]
    engine, resources = make_world(clock, n_res=4, n_clients=3)
    resident = ResidentDenseSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1
    )
    resident.step(resources)
    # Age past every lease (length 60).
    t[0] += 1000.0
    resident.step(resources)
    for res in resources:
        assert len(res.store) == 0
        assert res.store.sum_has == 0.0
        assert res.store.sum_wants == 0.0


def test_idle_fast_path_skips_device_work_until_something_changes():
    """Once a full rotation delivered with no changes, ticks cost no
    device work; any store write, capacity flip, or expiry resumes real
    solves and the change still lands in the store."""
    t = [100.0]
    clock = lambda: t[0]
    engine, resources = make_prop_world(clock, n_res=6)
    solver = ResidentDenseSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=2
    )
    # Converge, then run two full quiet rotations (rotate_ticks=2
    # means idling starts on the 6th quiet tick).
    for _ in range(9):
        solver.step(resources)
        t[0] += 1.0
    assert solver.idle_ticks > 0, "idle path never engaged"
    idle_before = solver.idle_ticks
    ticks_before = solver.ticks
    for _ in range(3):
        solver.step(resources)
        t[0] += 1.0
    assert solver.idle_ticks == idle_before + 3  # all skipped
    assert solver.ticks == ticks_before + 3  # but still counted

    # A store write resumes real ticks and reaches the store.
    resources[2].store.assign("c2_0", 60.0, 5.0,
                              resources[2].store.get("c2_0").has, 999.0, 1)
    solver.step(resources)
    assert solver.idle_ticks == idle_before + 3  # this one was real
    assert resources[2].store.get("c2_0").wants == 999.0
    changed_has = resources[2].store.get("c2_0").has
    assert changed_has > 0

    # Idle re-engages after another two quiet rotations...
    for _ in range(9):
        solver.step(resources)
        t[0] += 1.0
    assert solver.idle_ticks > idle_before + 3

    # ...and a capacity cut (epoch bump) breaks it same-tick.
    for res in resources:
        res.template.capacity = 100.0
    solver.step(resources, config_epoch=1)
    for res in resources:
        assert res.store.sum_has <= 100.0 + 1e-9


def test_dead_client_expires_on_schedule_while_server_stays_active():
    """Reference semantics: a lease's expiry advances only when ITS
    client refreshes (Decide stamps the requester; store.go:153-181).
    Delivery must not renew leases, or a crashed client would hold its
    capacity forever on any server that keeps ticking."""
    t = [100.0]
    clock = lambda: t[0]
    engine, resources = make_prop_world(clock, n_res=4)  # lease 60s
    solver = ResidentDenseSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=2
    )
    solver.step(resources)
    res0 = resources[0]
    assert res0.store.has_client("c0_0")

    # 10 ticks x 10s: every client except c0_0 keeps refreshing, so the
    # server never idles and deliveries keep landing on row 0.
    for _ in range(10):
        t[0] += 10.0
        for r, res in enumerate(resources):
            for c in range(5):
                if (r, c) == (0, 0):
                    continue  # the crashed client
                name = f"c{r}_{c}"
                lease = res.store.get(name)
                res.store.assign(name, 60.0, 5.0, lease.has,
                                 lease.wants, 1)
        solver.step(resources)

    # The dead client lapsed one lease length after its last refresh,
    # and its capacity was reclaimed by the others.
    assert not res0.store.has_client("c0_0"), (
        "delivery renewed a dead client's lease"
    )
    assert len(res0.store) == 4
    assert res0.store.sum_has == pytest.approx(1000.0)  # redistributed
