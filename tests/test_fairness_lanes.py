"""The device-resident fairness portfolio: MAX_MIN_FAIR,
BALANCED_FAIRNESS and PROPORTIONAL_FAIRNESS as compiled-away lanes of
the fused scoped tick.

Pins the acceptance surface of the portfolio (ISSUE 15):

  * host-reference parity: every lane's tick output is pinned to its
    numpy oracle (algorithms.tick) — exact for the pointwise steps,
    <= 1-ulp-scale for the bounded iterative fills (the FAIR_SHARE
    precedent) — through the BatchSolver AND through the scoped/fused
    resident tick on all four resident paths (narrow/wide x
    single-device/mesh; the mesh legs need the forced 8-device CPU of
    the multichip CI job);
  * scoped/fused byte identity: scoped-vs-full stores are IDENTICAL
    over seeded churn for a mixed ALL-lane resource table, per path;
  * compile-away: a lane absent from the static kind set leaves NO
    trace in the solve executable (jaxpr pin: the proportional-only
    solve lowers without a single `while` — every iterative fill is
    gone) and the per-tick dispatch/launch count is identical across
    lane choices (the launch-structure pin behind the bench's
    compile-away row);
  * config-epoch handling: flipping a template's `variant` parameter
    re-maps the lane through algo_kind_for and the next tick solves
    with the new lane's math;
  * federation: each lane's compact summary reconciles into per-shard
    shares whose local (per-shard) solve recovers the global
    allocation.
"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.algorithms import tick as tick_oracles
from doorman_tpu.algorithms.kinds import AlgoKind
from doorman_tpu.core.resource import Resource, algo_kind_for
from doorman_tpu.parallel import make_mesh
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.solver.resident import ResidentDenseSolver
from doorman_tpu.solver.resident_wide import WideResidentSolver
from doorman_tpu.utils import dispatch as dispatch_mod
from tests.test_engine import assert_store_parity, conformance_churn
from tests.test_resident_solver import all_leases

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

PATHS = ("resident", "resident_mesh", "wide", "wide_mesh")

# (wire kind, variant) per lane — the whole portfolio plus the
# reference lanes it must coexist with in one mixed table.
LANE_TEMPLATES = [
    (pb.Algorithm.PROPORTIONAL_SHARE, None),
    (pb.Algorithm.FAIR_SHARE, None),
    (pb.Algorithm.FAIR_SHARE, "maxmin"),
    (pb.Algorithm.FAIR_SHARE, "balanced"),
    (pb.Algorithm.PROPORTIONAL_SHARE, "logutil"),
    (pb.Algorithm.NO_ALGORITHM, None),
    (pb.Algorithm.STATIC, None),
]

NEW_LANES = (
    AlgoKind.MAX_MIN_FAIR,
    AlgoKind.BALANCED_FAIRNESS,
    AlgoKind.PROPORTIONAL_FAIRNESS,
)


def _template(r, wire_kind, variant, capacity):
    algo = pb.Algorithm(
        kind=int(wire_kind), lease_length=60, refresh_interval=5
    )
    if variant is not None:
        algo.parameters.add(name="variant", value=variant)
    return pb.ResourceTemplate(
        identifier_glob=f"res{r}", capacity=capacity, algorithm=algo
    )


def make_portfolio_world(clock, n_res=14, n_clients=9, seed=7):
    """One engine + resources cycling through EVERY lane, with varied
    subclients (so the subclient-weighted lanes genuinely diverge from
    the client-granular one) and integer demand (exactly-representable
    inputs: the repo's bit-parity convention)."""
    rng = np.random.default_rng(seed)
    engine = native.StoreEngine(clock=clock)
    resources = []
    for r in range(n_res):
        wire_kind, variant = LANE_TEMPLATES[r % len(LANE_TEMPLATES)]
        tpl = _template(
            r, wire_kind, variant, float(rng.integers(50, 400))
        )
        res = Resource(
            f"res{r}", tpl, clock=clock, store_factory=engine.store
        )
        resources.append(res)
        for c in range(n_clients):
            res.store.assign(
                f"c{r}_{c}", 60.0, 5.0, 0.0,
                float(rng.integers(1, 100)), int(rng.integers(1, 5)),
            )
    return engine, resources


def _make(path, engine, clock, scoped=True, fused=True):
    mesh = make_mesh() if path.endswith("_mesh") else None
    if path.startswith("resident"):
        return ResidentDenseSolver(
            engine, dtype=np.float64, clock=clock, rotate_ticks=1,
            mesh=mesh, fused=fused, scoped=scoped,
        )
    return WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8, mesh=mesh, fused=fused, scoped=scoped,
    )


def _oracle_for(res, wants, has, sub):
    from doorman_tpu.core.resource import static_param

    return tick_oracles.oracle_row(
        algo_kind_for(res.template), res.capacity,
        static_param(res.template), wants, has, sub,
    )


# ---------------------------------------------------------------------
# host-reference parity through the full stack
# ---------------------------------------------------------------------


@pytest.mark.parametrize("path", PATHS)
def test_first_tick_pinned_to_host_oracles(path):
    """The first full-delivery tick solves every lane from (wants,
    has=0) — its stores must match each lane's numpy oracle. Narrow
    paths bit-identical on these exactly-representable inputs; the
    wide paths carry their documented reassociation tolerance, and the
    iterative fills their <= 1-ulp budget (rtol 1e-12 covers both)."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_portfolio_world(clock)
    solver = _make(path, engine, clock)
    solver.step(resources, 0)
    exercised = set()
    for res in resources:
        names = sorted(c for c, _ in res.store.items())
        leases = [res.store.get(c) for c in names]
        wants = np.array([l.wants for l in leases])
        got = np.array([l.has for l in leases])
        sub = np.array([float(l.subclients) for l in leases])
        expected = _oracle_for(
            res, wants, np.zeros_like(wants), sub
        )
        np.testing.assert_allclose(
            got, expected, rtol=1e-12, atol=0,
            err_msg=f"{path} {res.id} "
                    f"lane {AlgoKind(algo_kind_for(res.template)).name}",
        )
        exercised.add(algo_kind_for(res.template))
    assert {int(k) for k in NEW_LANES} <= exercised


@pytest.mark.parametrize("path", PATHS)
def test_scoped_vs_full_byte_identity_all_lanes(path):
    """Scoped vs full solves over the mixed all-lane table: stores
    byte-identical every tick, per resident path, with the narrow
    paths' changed-rid streams equal too (the streaming-push input)."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_portfolio_world(clock)
    eng_b, res_b = make_portfolio_world(clock)
    full = _make(path, eng_a, clock, scoped=False)
    scoped = _make(path, eng_b, clock, scoped=True)
    track = path.startswith("resident")
    if track:
        assert full.enable_delta_tracking()
        assert scoped.enable_delta_tracking()
    rng_a, rng_b = (np.random.default_rng(31) for _ in range(2))
    scoped_ran = 0
    for step in range(8):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        full.step(res_a, 0)
        scoped.step(res_b, 0)
        ref, got = all_leases(res_a), all_leases(res_b)
        assert ref.keys() == got.keys(), f"{path} step {step}"
        for key in ref:
            assert got[key] == ref[key], (
                f"{path} step {step} lease {key}: "
                f"{got[key]} != {ref[key]}"
            )
        if track:
            assert (
                sorted(full.take_changed_rids())
                == sorted(scoped.take_changed_rids())
            ), f"{path} step {step}: changed-rid streams diverged"
        if scoped.last_solve_mode == "scoped":
            scoped_ran += 1
        t[0] += 1.0
    assert scoped_ran >= 4, scoped.solve_modes


@pytest.mark.parametrize("path", ("resident", "wide"))
def test_steady_churn_matches_batch_ground_truth(path):
    """Scoped/fused resident ticks over the all-lane world track the
    BatchSolver ground truth (itself pinned to the oracles) through
    churn — membership changes, releases, both bf16 encodings."""
    from doorman_tpu.solver.batch import BatchSolver
    from doorman_tpu.solver.engine import BatchTickAdapter

    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_portfolio_world(clock)
    eng_b, res_b = make_portfolio_world(clock)
    batch = BatchTickAdapter(BatchSolver(dtype=np.float64, clock=clock))
    solver = _make(path, eng_b, clock, scoped=True)
    rng_a, rng_b = (np.random.default_rng(47) for _ in range(2))
    for step in range(6):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        batch.step(res_a, 0)
        solver.step(res_b, 0)
        assert_store_parity(
            all_leases(res_a), all_leases(res_b), path, f"step {step}"
        )
        t[0] += 1.0


def test_batch_solver_pins_every_lane_to_oracle():
    """The BatchSolver leg of the parity ladder: one snapshot solve of
    the portfolio world equals the per-lane oracles directly (so the
    resident-vs-batch pins above chain back to the host references)."""
    from doorman_tpu.solver.batch import BatchSolver
    from doorman_tpu.solver.engine import BatchTickAdapter

    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_portfolio_world(clock)
    batch = BatchTickAdapter(BatchSolver(dtype=np.float64, clock=clock))
    batch.step(resources, 0)
    for res in resources:
        names = sorted(c for c, _ in res.store.items())
        leases = [res.store.get(c) for c in names]
        wants = np.array([l.wants for l in leases])
        got = np.array([l.has for l in leases])
        sub = np.array([float(l.subclients) for l in leases])
        expected = _oracle_for(res, wants, np.zeros_like(wants), sub)
        np.testing.assert_allclose(
            got, expected, rtol=1e-12, atol=0, err_msg=res.id
        )


def test_portfolio_lanes_genuinely_differ():
    """The lanes are a portfolio, not aliases: on a table with varied
    subclients and an overloaded pool, MAX_MIN_FAIR (client-granular)
    diverges from FAIR_SHARE (subclient-weighted), and the truncated
    BALANCED_FAIRNESS recursion may under-fill where the efficient
    lanes exhaust. PROPORTIONAL_FAIRNESS's dual fixpoint agrees with
    FAIR_SHARE's bisection at convergence (the single-capacity KKT
    coincidence, doc/algorithms.md) — within iteration tolerance, NOT
    necessarily bitwise."""
    wants = np.array([80.0, 30.0, 10.0, 60.0])
    sub = np.array([4.0, 1.0, 2.0, 1.0])
    cap = 100.0
    fair = tick_oracles.fair_share_waterfill(cap, wants, sub)
    maxmin = tick_oracles.max_min_fair_tick(cap, wants)
    pf = tick_oracles.proportional_fairness_tick(cap, wants, sub)
    bal = tick_oracles.balanced_fairness_tick(cap, wants, sub)
    assert not np.allclose(fair, maxmin)
    np.testing.assert_allclose(pf, fair, rtol=1e-9)
    assert bal.sum() <= cap + 1e-9
    assert (bal <= wants + 1e-12).all()


# ---------------------------------------------------------------------
# compile-away
# ---------------------------------------------------------------------


def _mixed_batch(kinds):
    import jax.numpy as jnp

    from doorman_tpu.solver.dense import DenseBatch

    rng = np.random.default_rng(3)
    R, K = len(kinds), 8
    return DenseBatch(
        wants=jnp.asarray(rng.integers(0, 50, (R, K)).astype(float)),
        has=jnp.asarray(rng.integers(0, 20, (R, K)).astype(float)),
        subclients=jnp.asarray(np.ones((R, K))),
        active=jnp.asarray(np.ones((R, K), bool)),
        capacity=jnp.asarray(np.full(R, 60.0)),
        algo_kind=jnp.asarray(np.asarray(kinds, np.int32)),
        learning=jnp.asarray(np.zeros(R, bool)),
        static_capacity=jnp.asarray(np.zeros(R)),
    )


def _has_loop(jaxpr_text: str) -> bool:
    # fori_loop lowers to `scan` when the trip count is static and
    # `while` otherwise; either marks an iterative fill.
    return "scan" in jaxpr_text or "while" in jaxpr_text


def test_absent_lanes_compile_away_jaxpr_pin():
    """The masking-seam pin at the jaxpr level: with only
    PROPORTIONAL_SHARE in the static kind set, the lowered solve
    contains NO loop primitive (every iterative fill — FAIR_SHARE's
    bisection and all three portfolio fills — is gone, not masked);
    each portfolio lane added to the set brings its loop back."""
    import jax

    from doorman_tpu.solver.dense import solve_dense

    prop = int(AlgoKind.PROPORTIONAL_SHARE)
    batch = _mixed_batch([prop] * 4)
    base = jax.make_jaxpr(
        lambda b: solve_dense(b, lanes=frozenset({prop}))
    )(batch)
    assert not _has_loop(str(base)), (
        "proportional-only solve still lowers an iterative fill"
    )
    for lane in NEW_LANES:
        with_lane = jax.make_jaxpr(
            lambda b: solve_dense(
                b, lanes=frozenset({prop, int(lane)})
            )
        )(batch)
        assert _has_loop(str(with_lane)), AlgoKind(lane).name
        # And removing it again restores the baseline jaxpr exactly.
        again = jax.make_jaxpr(
            lambda b: solve_dense(b, lanes=frozenset({prop}))
        )(batch)
        assert str(again) == str(base)


def test_lane_choice_never_changes_launch_structure():
    """The launch-count pin behind the bench's compile-away row: a
    steady fused+scoped tick costs the SAME number of device
    dispatches whichever single lane the table runs — lanes change
    executable content, never launch structure."""
    counts = {}
    for label, wire_kind, variant in (
        ("prop", pb.Algorithm.PROPORTIONAL_SHARE, None),
        ("maxmin", pb.Algorithm.FAIR_SHARE, "maxmin"),
        ("balanced", pb.Algorithm.FAIR_SHARE, "balanced"),
        ("logutil", pb.Algorithm.PROPORTIONAL_SHARE, "logutil"),
    ):
        t = [1000.0]
        clock = lambda: t[0]  # noqa: E731
        rng = np.random.default_rng(5)
        engine = native.StoreEngine(clock=clock)
        resources = []
        for r in range(8):
            tpl = _template(r, wire_kind, variant, 100.0)
            res = Resource(
                f"res{r}", tpl, clock=clock, store_factory=engine.store
            )
            resources.append(res)
            for c in range(6):
                res.store.assign(
                    f"c{r}_{c}", 60.0, 5.0, 0.0,
                    float(rng.integers(1, 60)), 1,
                )
        solver = _make("resident", engine, clock, scoped=True)
        solver.step(resources, 0)  # rebuild + compile
        per_tick = []
        for step in range(3):
            resources[step % 8].store.assign(
                f"c{step % 8}_0", 60.0, 5.0,
                resources[step % 8].store.get(f"c{step % 8}_0").has,
                float(rng.integers(1, 60)), 1,
            )
            mark = dispatch_mod.snapshot()
            solver.step(resources, 0)
            per_tick.append(dispatch_mod.delta(mark)["dispatches"])
            t[0] += 1.0
        counts[label] = per_tick
    assert len({tuple(v) for v in counts.values()}) == 1, counts


# ---------------------------------------------------------------------
# config-epoch handling
# ---------------------------------------------------------------------


def test_variant_flip_remaps_lane_on_config_epoch():
    """A config reload that only flips the `variant` parameter re-maps
    the device lane (algo_kind_for feeds the solver's config mirror):
    the next tick solves with the NEW lane's math — pinned by oracle
    comparison on both sides of the flip."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    rng = np.random.default_rng(13)
    engine = native.StoreEngine(clock=clock)
    tpl = _template(0, pb.Algorithm.FAIR_SHARE, None, 100.0)
    res = Resource("res0", tpl, clock=clock, store_factory=engine.store)
    for c in range(7):
        res.store.assign(
            f"c{c}", 60.0, 5.0, 0.0,
            float(rng.integers(20, 90)), int(rng.integers(1, 5)),
        )
    solver = _make("resident", engine, clock, scoped=True)
    solver.step([res], 0)
    names = sorted(c for c, _ in res.store.items())
    wants = np.array([res.store.get(c).wants for c in names])
    sub = np.array([float(res.store.get(c).subclients) for c in names])
    got = np.array([res.store.get(c).has for c in names])
    np.testing.assert_allclose(
        got, tick_oracles.fair_share_waterfill(100.0, wants, sub),
        rtol=1e-12,
    )
    # The reload: same wire kind, new variant.
    res.load_config(
        _template(0, pb.Algorithm.FAIR_SHARE, "maxmin", 100.0), None
    )
    assert algo_kind_for(res.template) == int(AlgoKind.MAX_MIN_FAIR)
    res.store.assign(
        names[0], 60.0, 5.0, res.store.get(names[0]).has,
        float(wants[0]), int(sub[0]),
    )
    solver.step([res], 1)  # epoch bump: mirror re-reads the kind vector
    assert solver.last_full_reason == "config-epoch"
    got = np.array([res.store.get(c).has for c in names])
    np.testing.assert_allclose(
        got, tick_oracles.max_min_fair_tick(100.0, wants), rtol=1e-12
    )


# ---------------------------------------------------------------------
# federation share derivation
# ---------------------------------------------------------------------


@pytest.mark.parametrize(
    "lane", [int(k) for k in NEW_LANES], ids=[k.name for k in NEW_LANES]
)
def test_sharded_shares_recover_global_allocation(lane):
    """Each new lane's compact summary reconciles into per-shard
    shares whose LOCAL solve (the lane's own tick oracle over only the
    shard's clients at its share) reproduces the GLOBAL allocation —
    the POP decomposition extended to the portfolio."""
    from doorman_tpu.federation.reconcile import (
        ShardSummary,
        StraddleReconciler,
        _UNWEIGHTED_KINDS,
    )

    rng = np.random.default_rng(lane)
    cap = 300.0
    shards = {0: [], 1: [], 2: []}
    for i in range(18):
        shards[i % 3].append(
            (float(rng.integers(10, 80)), float(rng.integers(1, 5)))
        )

    def solve(kind, capacity, wants, sub):
        if kind == int(AlgoKind.MAX_MIN_FAIR):
            return tick_oracles.max_min_fair_tick(capacity, wants)
        if kind == int(AlgoKind.BALANCED_FAIRNESS):
            return tick_oracles.balanced_fairness_tick(
                capacity, wants, sub
            )
        return tick_oracles.proportional_fairness_tick(
            capacity, wants, sub
        )

    all_wants = np.array([w for cl in shards.values() for (w, _s) in cl])
    all_sub = np.array([s for cl in shards.values() for (_w, s) in cl])
    global_gets = solve(lane, cap, all_wants, all_sub)
    assert all_wants.sum() > cap  # overloaded, or the split is trivial

    def summary(shard, clients):
        by_ratio = {}
        wants_sum = weight_sum = 0.0
        for w, s in clients:
            weight = 1.0 if lane in _UNWEIGHTED_KINDS else s
            acc = by_ratio.setdefault(w / weight, [0.0, 0.0])
            acc[0] += w
            acc[1] += weight
            wants_sum += w
            weight_sum += weight
        return ShardSummary(
            shard=shard, wants=wants_sum, weight=weight_sum,
            breakpoints=tuple(
                (r, by_ratio[r][0], by_ratio[r][1])
                for r in sorted(by_ratio)
            ),
        )

    rec = StraddleReconciler(
        "r0", cap, lane, share_ttl=10.0, lease_length=5.0
    )
    shares = rec.reconcile(
        {s: summary(s, cl) for s, cl in shards.items()}, now=0.0
    )
    assert sum(shares.values()) <= cap * (1 + 1e-12)
    pos = 0
    for s, clients in shards.items():
        wants = np.array([w for (w, _s) in clients])
        sub = np.array([x for (_w, x) in clients])
        local = solve(lane, shares[s], wants, sub)
        np.testing.assert_allclose(
            local, global_gets[pos : pos + len(clients)],
            rtol=1e-9, atol=1e-9,
            err_msg=f"shard {s} local solve diverged from global",
        )
        pos += len(clients)


def test_reconciler_accepts_portfolio_kinds():
    from doorman_tpu.federation.reconcile import CAPACITY_SPLIT_KINDS

    for lane in NEW_LANES:
        assert int(lane) in CAPACITY_SPLIT_KINDS
