"""System-level soak: the real stack end-to-end through config reload
and master failover under live traffic.

Two CapacityServers share one election KV (the real KVElection state
machine); clients run the framework's own client library (master-aware
connection, background refresh loop) against loopback gRPC. The
timeline replays the reference's system-validation scenarios on the
REAL server instead of the simulation (reference scenario 2/3:
master loss and re-election; doc/design.md:773-799):

  A. converge on the initial capacity through the resident tick path;
  B. hot config reload cuts capacity — grants shrink within ticks;
  C. the master's lock expires (fault injection); mastership moves,
     the new master relearns from client reports, and client-side
     capacity NEVER collapses (leases persist through the outage,
     learning replays them — the reference's failover story).
"""

import asyncio

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.client.client import Client
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import InMemoryKV, KVElection
from doorman_tpu.server.server import CapacityServer

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)


def _config(cap):
    return parse_yaml_config(
        f"""
resources:
- identifier_glob: "shared"
  capacity: {cap}
  algorithm: {{kind: PROPORTIONAL_SHARE, lease_length: 60,
               refresh_interval: 1, learning_mode_duration: 1}}
- identifier_glob: "*"
  capacity: 300
  algorithm: {{kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
               learning_mode_duration: 1}}
"""
    )


def _master_of(servers):
    masters = [s for s in servers if s.is_master]
    return masters[0] if len(masters) == 1 else None


async def _wait(predicate, timeout=15.0, interval=0.05):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        value = predicate()
        if value:
            return value
        await asyncio.sleep(interval)
    raise AssertionError("condition not reached in time")


@pytest.mark.parametrize("native", [True, False])
def test_soak_reload_and_failover_under_live_traffic(native):
    async def body():
        kv = InMemoryKV()
        servers = []
        for _ in range(2):
            server = CapacityServer(
                "pending", KVElection(kv, "/doorman/soak", ttl=0.6),
                mode="batch", tick_interval=0.05,
                minimum_refresh_interval=0.0, native_store=native,
            )
            port = await server.start(0, host="127.0.0.1")
            # In production the server id IS its address
            # (cmd/server.py); mastership redirects carry it.
            server.id = f"127.0.0.1:{port}"
            servers.append(server)
        for server in servers:
            await server.load_config(_config(1000))

        master = await _wait(lambda: _master_of(servers))

        # 10 clients on the oversubscribed "shared" resource through
        # the real client library; half dial the standby to exercise
        # the mastership redirect.
        clients, resources = [], []
        for i in range(10):
            client = await Client.connect(
                servers[i % 2].id, client_id=f"soak{i}",
                minimum_refresh_interval=0.0,
            )
            clients.append(client)
            resources.append(await client.resource("shared", 200.0))

        def total():
            return sum(r.current_capacity() for r in resources)

        # Phase A: converge to the full 1000 (10 x 200 wants > 1000).
        await _wait(lambda: abs(total() - 1000.0) < 1e-6)
        store = master.resources["shared"].store
        assert store.sum_has <= 1000.0 + 1e-6

        # Phase B: hot reload cuts capacity to 400 on both servers (a
        # shared config source would do the same); grants shrink to the
        # new cap within ticks and client refreshes.
        for server in servers:
            await server.load_config(_config(400))
        await _wait(lambda: abs(total() - 400.0) < 1e-6)
        assert master.resources["shared"].store.sum_has <= 400.0 + 1e-6

        # Phase C: the master's lock lapses. Mastership moves (either
        # task may win the next campaign), the winner starts in
        # learning mode and replays client-reported grants.
        old_master = master
        lows = []

        async def sampler():
            while True:
                lows.append(total())
                await asyncio.sleep(0.05)

        sampling = asyncio.create_task(sampler())
        won_at = old_master.became_master_at
        kv.expire("/doorman/soak")
        # The incumbent notices the lapsed lock at its next renewal,
        # steps down (wiping all lease state), and a campaign decides a
        # NEW mastership (either task can win; the incumbent often
        # re-wins instantly, so detect the transition by a fresh
        # became_master_at rather than a visible not-master window).
        new_master = await _wait(
            lambda: next(
                (
                    s for s in servers
                    if s.is_master and s.became_master_at != won_at
                ),
                None,
            ),
            timeout=20,
        )
        # Clients keep refreshing against the new master (redirects) and
        # converge back to the cut capacity.
        await _wait(
            lambda: new_master.resources.get("shared") is not None
            and abs(total() - 400.0) < 1e-6,
            timeout=20,
        )
        sampling.cancel()

        # The failover never collapsed client-side capacity: leases
        # persist through the outage and learning mode replays them
        # (reference doc/design.md failover story). Allow transient
        # redistribution but no crash toward zero.
        assert min(lows) >= 200.0, f"capacity collapsed: min={min(lows)}"
        assert new_master.resources["shared"].store.sum_has <= 400.0 + 1e-6
        # Every client ends with a live grant.
        assert all(r.current_capacity() > 0 for r in resources)

        for client in clients:
            await client.close()
        for server in servers:
            await server.stop()

    asyncio.run(body())
