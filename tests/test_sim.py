"""Simulation harness tests: scheduler mechanics plus scenario-level
regression assertions mirroring the reference's published behavior
(doc/design.md:773-799): high utilization in steady state, lease-expiry
outage in scenario 3, recovery from mishaps in scenario 7."""

import pytest

from doorman_tpu.sim.core import Sim
from doorman_tpu.sim.scenarios import run_scenario


class TestScheduler:
    def test_absolute_and_relative_ordering(self):
        sim = Sim()
        order = []
        sim.scheduler.add_absolute(10, lambda: order.append("a"))
        sim.scheduler.add_absolute(5, lambda: order.append("b"))
        sim.scheduler.add_relative(7, lambda: order.append("c"))
        sim.scheduler.loop(20)
        assert order == ["b", "c", "a"]
        assert sim.clock.get_time() == 20

    def test_threads_reschedule(self):
        sim = Sim()
        runs = []

        class T:
            def thread_continue(self):
                runs.append(sim.clock.get_time())
                return 10.0

        sim.scheduler.add_thread(T(), 0.0)
        sim.scheduler.loop(35)
        assert runs == [0.0, 10.0, 20.0, 30.0]

    def test_finalizers_run(self):
        sim = Sim()
        done = []
        sim.scheduler.add_finalizer(lambda: done.append(True))
        sim.scheduler.loop(1)
        assert done == [True]

    def test_action_scheduling_action_same_time(self):
        sim = Sim()
        order = []

        def outer():
            order.append("outer")
            sim.scheduler.add_absolute(
                sim.clock.get_time(), lambda: order.append("inner")
            )

        sim.scheduler.add_absolute(5, outer)
        sim.scheduler.loop(10)
        assert order == ["outer", "inner"]


class TestScenarios:
    def test_scenario_one_converges(self):
        sim, reporter = run_scenario("1", run_for=300)
        s = reporter.summary()
        # 5 clients wanting ~110 each against capacity 500: overload, and
        # nearly all capacity is handed out after learning.
        assert s["utilization"] > 0.85
        assert s["overage_events"] == 0

    @pytest.mark.parametrize(
        "name", ["1_fair", "1_maxmin", "1_balanced", "1_logutil"]
    )
    def test_scenario_one_converges_per_fairness_lane(self, name):
        """The scenario-one convergence arc holds for every
        fairness-portfolio lane: high utilization after learning,
        never an overage (balanced fairness may leave a little more
        slack by design — the insensitivity truncation — so its floor
        is the only relaxed one)."""
        sim, reporter = run_scenario(name, run_for=300)
        s = reporter.summary()
        floor = 0.75 if name == "1_balanced" else 0.85
        assert s["utilization"] > floor, (name, s)
        assert s["overage_events"] == 0, (name, s)

    def test_scenario_two_master_loss_before_expiry(self):
        sim, reporter = run_scenario("2", run_for=300)
        # Re-election at T=140 lands within the 60s lease: clients keep
        # their grants and utilization stays high.
        assert reporter.summary()["utilization"] > 0.85
        assert sim.varz.counter("client.lease_expired").value == 0

    def test_scenario_three_lease_expiry_outage(self):
        sim, reporter = run_scenario("3", run_for=300)
        # Re-election at T=190 is past lease expiry: leases lapse.
        assert sim.varz.counter("client.lease_expired").value > 0
        # And the outage dents utilization relative to scenario 2.
        _, r2 = run_scenario("2", run_for=300)
        assert (
            reporter.summary()["utilization"]
            < r2.summary()["utilization"]
        )

    def test_scenario_four_two_level_tree(self):
        sim, reporter = run_scenario("4", run_for=300)
        assert reporter.summary()["utilization"] > 0.8

    def test_scenario_five_three_level_tree(self):
        sim, reporter = run_scenario("5", run_for=300)
        # Reference quotes 96.8% for this topology (doc/design.md:787).
        assert reporter.summary()["utilization"] > 0.9
        assert len(sim.clients) == 45

    def test_scenario_six_demand_spike(self):
        sim, reporter = run_scenario("6", run_for=300)
        s = reporter.summary()
        assert s["utilization"] > 0.85
        # The two spiking clients dominate after T=150 but never push the
        # total over capacity.
        assert s["overage_events"] == 0

    def test_scenario_seven_mishaps_recover(self):
        sim, reporter = run_scenario("7", run_for=900)
        s = reporter.summary()
        # Mishaps (master loss, elections, spikes) happened...
        mishaps = sum(
            c.value for c in sim.varz.counters() if c.name.startswith("mishap.")
        )
        assert mishaps > 0
        # ...and the system still hands out most of the capacity on
        # average (reference quotes 96.6% over an hour with mishaps).
        assert s["utilization"] > 0.8

    def test_scenario_seven_hour_fidelity(self):
        """Full-fidelity parity run: the reference's simulated hour with
        weighted mishaps (doc/design.md:787-799 quotes 96.6% utilization,
        14 shortfalls, max 530.24 = 106%, avg overage 509.99 = 102%).
        The run is deterministic given the seed, so the bounds pin the
        behavior, not luck; doc/parity.md quotes the measured numbers."""
        sim, reporter = run_scenario("7")  # default duration: 3600s
        s = reporter.summary()
        assert s["samples"] >= 600  # ~an hour of 5s samples, post-warmup
        assert s["utilization"] >= 0.96, s
        # Shortfall statistics in the reference's neighborhood: a
        # handful of events, magnitude a few percent over capacity.
        assert 1 <= s["overage_events"] <= 25, s
        assert s["max_overage"] <= 500 * 1.15, s
        assert 500 < s["avg_overage"] <= 500 * 1.05, s
        # The weighted mishap mix (election 1/15, spike 10/15,
        # lose_master 4/15 — reference scenario_seven.py:54-78 under
        # py2 dict order) is what the hour actually exercised.
        m = {
            c.name: c.value
            for c in sim.varz.counters()
            if c.name.startswith("mishap.")
        }
        assert m.get("mishap.spike", 0) > m.get("mishap.lose_master", 0)
        assert m.get("mishap.lose_master", 0) > m.get("mishap.election", 0)

    def test_deterministic_given_seed(self):
        _, r1 = run_scenario("1", run_for=120, seed=7)
        _, r2 = run_scenario("1", run_for=120, seed=7)
        assert r1.summary() == r2.summary()


def test_cli_all_runs_every_scenario():
    """`python -m doorman_tpu.sim all` is the counterpart of the
    reference's run_all_scenarios.sh: one JSON summary line per
    scenario, all seven of them."""
    import json
    import pathlib
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-m", "doorman_tpu.sim", "all", "--run-for", "30"],
        capture_output=True, text=True, timeout=300,
        cwd=pathlib.Path(__file__).resolve().parent.parent,
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    assert [json.loads(l)["scenario"] for l in lines] == list("1234567")
