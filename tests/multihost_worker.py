"""Worker half of the REAL two-process multi-host test.

Launched twice by tests/test_multihost.py::test_two_process_distributed_solve
with DOORMAN_COORDINATOR / DOORMAN_NUM_PROCESSES / DOORMAN_PROCESS_ID in
the environment — the exact wiring a production multi-host deployment
uses (parallel/multihost.py `initialize`). Each process owns 2 virtual
CPU devices and ONLY its own half of the edge table; the global sharded
solve must still equal the single-device full-table solve, proving the
host-local packing + process-ordered mesh + cross-process psum really
compose (not just the single-process simulation of them the unit tests
cover).

Prints MULTIHOST WORKER OK on success; any mismatch raises.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
# Cross-process CPU collectives need an explicit implementation; gloo
# ships with jax's CPU PJRT plugin.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.config.update("jax_enable_x64", True)

import numpy as np


def main() -> None:
    from doorman_tpu.parallel import make_sharded_solver, multihost
    from doorman_tpu.parallel.sharded import replicate_resources
    from doorman_tpu.solver.kernels import solve_tick

    from __graft_entry__ import _example_batch

    multihost.initialize()  # DOORMAN_* env wiring under test
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.local_devices()) == 2

    # Both processes build the same deterministic global table, but each
    # feeds ONLY its own host block through the packing path.
    edges, resources = _example_batch(n_resources=8, edges_per_resource=16)
    n_edges = int(np.asarray(edges.active).shape[0])

    mesh = multihost.make_multihost_mesh(("dc", "clients"))
    blocks = multihost.split_edges_by_host(edges, jax.process_count())
    local = blocks[jax.process_index()]
    edges_per_host = n_edges // 2 + 6  # uneven block: exercises padding
    packed = multihost.pack_process_edges(
        mesh, local, edges_per_host=edges_per_host
    )
    gets = make_sharded_solver(mesh)(
        packed, replicate_resources(mesh, resources)
    )
    jax.block_until_ready(gets)

    # Expected global layout: host i's block (its slice of the
    # single-device full-table solve) padded to the agreed per-host
    # size with zeros (inactive edges solve to 0).
    expected_full = np.asarray(jax.jit(solve_tick)(edges, resources))
    per_host = n_edges // 2
    eph = edges_per_host + (-edges_per_host) % 2  # per-host device mult
    expected_global = np.zeros(eph * 2, expected_full.dtype)
    for h in range(2):
        expected_global[h * eph : h * eph + per_host] = expected_full[
            h * per_host : (h + 1) * per_host
        ]

    # Each process can only address its own shards: compare shard-wise.
    checked = 0
    for shard in gets.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(shard.data),
            expected_global[shard.index],
            rtol=1e-12,
            atol=1e-12,
        )
        checked += 1
    assert checked > 0, "process addressed no shards"
    print(f"MULTIHOST WORKER OK process={jax.process_index()} "
          f"shards={checked}", flush=True)


if __name__ == "__main__":
    main()
