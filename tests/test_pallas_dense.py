"""Parity of the pallas dense kernel against the XLA dense solve and the
numpy oracles (interpret mode on the CPU mesh; the TPU lowering is
exercised by bench.py's spot check on real hardware)."""

import numpy as np
import jax.numpy as jnp
import pytest

from doorman_tpu.algorithms import tick as oracle
from doorman_tpu.algorithms.kinds import AlgoKind
from doorman_tpu.solver.dense import DenseBatch, solve_dense
from doorman_tpu.solver.pallas_dense import solve_dense_pallas


def random_batch(rng, R, K, C, kinds=(0, 1, 2, 3, 4), learning_p=0.1):
    active = np.zeros((R, K), bool)
    for r in range(R):
        active[r, : rng.integers(1, C + 1)] = True
    dtype = np.float32
    return DenseBatch(
        wants=jnp.asarray((rng.integers(0, 100, (R, K)) * active), dtype),
        has=jnp.asarray((rng.integers(0, 50, (R, K)) * active), dtype),
        subclients=jnp.asarray(
            rng.integers(1, 4, (R, K)) * active, dtype
        ),
        active=jnp.asarray(active),
        capacity=jnp.asarray(rng.integers(50, 10_000, R), dtype),
        algo_kind=jnp.asarray(rng.choice(kinds, R), jnp.int32),
        learning=jnp.asarray(rng.random(R) < learning_p),
        static_capacity=jnp.asarray(rng.integers(1, 100, R), dtype),
    )


@pytest.mark.parametrize("R,K", [(7, 128), (300, 128), (40, 64)])
def test_pallas_matches_xla_dense(R, K):
    rng = np.random.default_rng(R * K)
    batch = random_batch(rng, R, K, min(K, 100))
    a = np.asarray(solve_dense(batch))
    b = np.asarray(solve_dense_pallas(batch, interpret=True))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-4)


def test_pallas_matches_numpy_oracles_per_kind():
    rng = np.random.default_rng(7)
    R, K, C = 60, 128, 100
    batch = random_batch(rng, R, K, C, learning_p=0.0)
    gets = np.asarray(solve_dense_pallas(batch, interpret=True))
    active = np.asarray(batch.active)
    wants = np.asarray(batch.wants, np.float64)
    has = np.asarray(batch.has, np.float64)
    sub = np.asarray(batch.subclients, np.float64)
    for r in range(R):
        m = active[r]
        w, h, s = wants[r, m], has[r, m], sub[r, m]
        c = float(batch.capacity[r])
        k = int(batch.algo_kind[r])
        if k == AlgoKind.NO_ALGORITHM:
            expected = oracle.none_tick(w)
        elif k == AlgoKind.STATIC:
            expected = oracle.static_tick(float(batch.static_capacity[r]), w)
        elif k == AlgoKind.PROPORTIONAL_SHARE:
            expected = oracle.proportional_snapshot(c, w, h)
        elif k == AlgoKind.PROPORTIONAL_TOPUP:
            expected = oracle.proportional_topup_snapshot(c, w, h, s)
        else:
            expected = oracle.fair_share_waterfill(c, w, s)
        np.testing.assert_allclose(
            gets[r, m].astype(np.float64), expected, rtol=2e-5, atol=1e-3,
            err_msg=f"resource {r} kind {k}",
        )


def test_pallas_learning_lane_and_padding():
    rng = np.random.default_rng(3)
    # R deliberately not a multiple of the row tile, K not of the lane
    # width: exercises both pad-and-slice paths.
    batch = random_batch(rng, 13, 64, 40, learning_p=1.0)
    gets = np.asarray(solve_dense_pallas(batch, interpret=True))
    active = np.asarray(batch.active)
    np.testing.assert_allclose(
        gets[active], np.asarray(batch.has)[active], rtol=1e-6
    )
    assert (gets[~active] == 0).all()
