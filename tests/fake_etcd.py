"""In-process fake etcd speaking the v3 HTTP/JSON gateway surface.

Implements exactly the endpoints the framework's shared gateway client
(doorman_tpu/server/etcd.py) uses — /v3/kv/range, /v3/kv/put,
/v3/kv/txn (create_revision==0 compare), /v3/lease/grant,
/v3/lease/keepalive, /v3/lease/revoke, and the streaming /v3/watch —
so the config source and the election lock integration-test against
the real HTTP dialect without an etcd binary. Leases expire on real
time (tests use sub-second TTLs); `expire_lease`/`drop_key` inject
faults.
"""

from __future__ import annotations

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple


def _b64d(s: str) -> str:
    return base64.b64decode(s).decode()


def _b64e(s: "str | bytes") -> str:
    if isinstance(s, str):
        s = s.encode()
    return base64.b64encode(s).decode()


class FakeEtcd:
    """The state machine + HTTP server. Start with `start()`; `address`
    is host:port for client endpoint lists."""

    def __init__(self):
        self.latency = 0.0  # per-request delay (slow-etcd fault injection)
        self._lock = threading.Lock()
        # key -> (value, lease_id, create_revision)
        self._kv: Dict[str, Tuple[str, int, int]] = {}
        # lease id -> (ttl_seconds, deadline)
        self._leases: Dict[int, Tuple[float, float]] = {}
        self._next_lease = 7_000_000_000_000_000_001
        self._revision = 1
        self._changed = threading.Condition(self._lock)
        self._server: Optional[ThreadingHTTPServer] = None

    # -- state machine (called under self._lock) ------------------------

    def _sweep(self) -> None:
        """Expire lapsed leases and the keys bound to them."""
        now = time.monotonic()
        dead = [i for i, (_, dl) in self._leases.items() if dl <= now]
        for lease_id in dead:
            del self._leases[lease_id]
            gone = [k for k, (_, l, _) in self._kv.items() if l == lease_id]
            for key in gone:
                del self._kv[key]
            if gone:
                self._changed.notify_all()

    def _put(self, key: str, value: str, lease_id: int) -> None:
        if lease_id and lease_id not in self._leases:
            # Real etcd rejects puts naming a revoked/unknown lease;
            # accepting them would create keys the sweep never expires.
            raise ValueError("etcdserver: requested lease not found")
        self._revision += 1
        prev = self._kv.get(key)
        create_rev = prev[2] if prev else self._revision
        self._kv[key] = (value, lease_id, create_rev)
        self._changed.notify_all()

    # -- fault injection -------------------------------------------------

    def expire_lease(self, lease_id: int) -> None:
        """As if the holder stopped renewing and the TTL lapsed."""
        with self._lock:
            self._leases.pop(lease_id, None)
            gone = [k for k, (_, l, _) in self._kv.items() if l == lease_id]
            for key in gone:
                del self._kv[key]
            self._changed.notify_all()

    def expire_key_lease(self, key: str) -> None:
        """Expire whatever lease currently holds `key`."""
        with self._lock:
            entry = self._kv.get(key)
        if entry and entry[1]:
            self.expire_lease(entry[1])

    def drop_key(self, key: str) -> None:
        with self._lock:
            self._kv.pop(key, None)
            self._changed.notify_all()

    def value(self, key: str) -> Optional[str]:
        with self._lock:
            self._sweep()
            entry = self._kv.get(key)
            return entry[0] if entry else None

    # -- HTTP endpoints ---------------------------------------------------

    def handle(self, path: str, body: dict, handler) -> Optional[dict]:
        """Returns a JSON-able response, or None if `handler` streamed
        the response itself (/v3/watch)."""
        if path == "/v3/kv/range":
            key = _b64d(body["key"])
            range_end = (
                _b64d(body["range_end"]) if body.get("range_end") else None
            )
            with self._lock:
                self._sweep()
                if range_end is None:
                    hits = (
                        [(key, self._kv[key])] if key in self._kv else []
                    )
                else:
                    hits = sorted(
                        (k, v)
                        for k, v in self._kv.items()
                        if key <= k < range_end
                    )
            if not hits:
                return {"count": "0"}
            return {
                "count": str(len(hits)),
                "kvs": [
                    {
                        "key": _b64e(k),
                        "value": _b64e(entry[0]),
                        "create_revision": str(entry[2]),
                    }
                    for k, entry in hits
                ],
            }
        if path == "/v3/kv/deleterange":
            key = _b64d(body["key"])
            range_end = (
                _b64d(body["range_end"]) if body.get("range_end") else None
            )
            with self._lock:
                self._sweep()
                if range_end is None:
                    gone = [key] if key in self._kv else []
                else:
                    gone = [
                        k for k in self._kv if key <= k < range_end
                    ]
                for k in gone:
                    del self._kv[k]
                if gone:
                    self._revision += 1
                    self._changed.notify_all()
            return {"deleted": str(len(gone))}
        if path == "/v3/kv/put":
            key = _b64d(body["key"])
            value = _b64d(body["value"])
            lease_id = int(body.get("lease", 0))
            with self._lock:
                self._sweep()
                self._put(key, value, lease_id)
            return {}
        if path == "/v3/kv/txn":
            return self._txn(body)
        if path == "/v3/lease/grant":
            ttl = float(body["TTL"])
            with self._lock:
                lease_id = self._next_lease
                self._next_lease += 1
                self._leases[lease_id] = (ttl, time.monotonic() + ttl)
            return {"ID": str(lease_id), "TTL": str(int(ttl))}
        if path == "/v3/lease/keepalive":
            lease_id = int(body["ID"])
            with self._lock:
                self._sweep()
                entry = self._leases.get(lease_id)
                if entry is None:
                    return {"result": {"ID": str(lease_id), "TTL": "0"}}
                ttl = entry[0]
                self._leases[lease_id] = (ttl, time.monotonic() + ttl)
            return {
                "result": {"ID": str(lease_id), "TTL": str(int(ttl))}
            }
        if path == "/v3/lease/revoke":
            lease_id = int(body["ID"])
            with self._lock:
                self._sweep()
                known = lease_id in self._leases
            if not known:
                # Real etcd errors on revoking an unknown/expired lease
                # (HTTP 400, "etcdserver: requested lease not found");
                # the election's _revoke_quietly treats that as
                # "unconfirmed" and keeps its backstop armed — a fake
                # that 200s here would hide that path.
                raise ValueError("etcdserver: requested lease not found")
            self.expire_lease(lease_id)
            return {}
        if path == "/v3/watch":
            self._watch(body, handler)
            return None
        raise ValueError(f"unhandled path {path}")

    def _txn(self, body: dict) -> dict:
        """Only the dialect the gateway client emits: a single compare
        on CREATE == 0 guarding request_put ops. Compare and guarded
        ops run under ONE lock acquisition — real etcd txns are atomic,
        and the election integration tests exist to pin exactly the
        mutual exclusion a split compare/put would break (two racing
        put_if_absent calls both told they won)."""
        with self._lock:
            self._sweep()
            succeeded = True
            for cmp in body.get("compare", []):
                target = cmp.get("target")
                key = _b64d(cmp["key"])
                entry = self._kv.get(key)
                if target == "CREATE":
                    expected = int(cmp.get("create_revision", 0))
                    actual = entry[2] if entry else 0
                    ok = actual == expected
                else:
                    raise ValueError(
                        f"unhandled txn compare target {target}"
                    )
                if cmp.get("result", "EQUAL") == "EQUAL":
                    succeeded = succeeded and ok
                else:
                    succeeded = succeeded and not ok
            ops = body.get("success" if succeeded else "failure", [])
            responses = []
            for op in ops:
                put = op.get("request_put") or op.get("requestPut")
                if put:
                    self._put(
                        _b64d(put["key"]),
                        _b64d(put["value"]),
                        int(put.get("lease", 0)),
                    )
                    responses.append({"response_put": {}})
        return {"succeeded": succeeded, "responses": responses}

    def _watch(self, body: dict, handler) -> None:
        """Streamed newline-delimited JSON: creation ack immediately,
        then one event frame when the key changes (then close)."""
        key = _b64d(body["create_request"]["key"])
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.end_headers()
        ack = json.dumps({"result": {"created": True}}) + "\n"
        handler.wfile.write(ack.encode())
        handler.wfile.flush()
        changed = False
        with self._lock:
            self._sweep()
            entry = self._kv.get(key)
            baseline = entry[0] if entry else None
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                self._sweep()
                entry = self._kv.get(key)
                current = entry[0] if entry else None
                if current != baseline:
                    changed = True
                    break
                self._changed.wait(timeout=0.2)
        # Write outside the lock: a stalled watch client must not block
        # every other request. On idle timeout just close the stream (no
        # phantom event) — the client treats a clean close as a healthy
        # idle watch, matching real etcd's no-event stream.
        if changed:
            event = {
                "result": {"events": [{"kv": {"key": _b64e(key)}}]}
            }
            handler.wfile.write((json.dumps(event) + "\n").encode())
            handler.wfile.flush()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> str:
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def do_POST(self):
                if fake.latency:
                    time.sleep(fake.latency)
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                try:
                    out = fake.handle(self.path, body, self)
                except Exception as e:  # pragma: no cover
                    self.send_response(400)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                if out is None:
                    return  # handler streamed its own response
                data = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):  # quiet
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    @property
    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
