"""Priority-banded group-capped allocation: oracle properties and
JAX-vs-oracle parity (BASELINE.json config 5)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from doorman_tpu.algorithms import priority as oracle
from doorman_tpu.algorithms.tick import fair_share_waterfill
from doorman_tpu.solver.priority import PriorityBatch, solve_priority

jax.config.update("jax_enable_x64", True)


# ---------------------------------------------------------------- oracle

def test_single_band_is_fair_share():
    rng = np.random.default_rng(0)
    wants = rng.integers(0, 100, 20).astype(float)
    weights = rng.integers(1, 4, 20).astype(float)
    got = oracle.priority_alloc(300.0, wants, weights, np.zeros(20, int))
    np.testing.assert_allclose(
        got, fair_share_waterfill(300.0, wants, weights)
    )


def test_higher_band_served_first():
    wants = np.array([50.0, 50.0, 80.0, 80.0])
    weights = np.ones(4)
    bands = np.array([0, 0, 1, 1])
    got = oracle.priority_alloc(120.0, wants, weights, bands)
    # Band 0 fits entirely (100), band 1 splits the 20 left over.
    np.testing.assert_allclose(got, [50, 50, 10, 10])
    # Capacity below band 0's demand: band 1 gets nothing.
    got = oracle.priority_alloc(60.0, wants, weights, bands)
    np.testing.assert_allclose(got, [30, 30, 0, 0])


def test_oracle_capacity_invariant():
    rng = np.random.default_rng(1)
    for _ in range(20):
        n = int(rng.integers(1, 30))
        wants = rng.integers(0, 100, n).astype(float)
        weights = rng.integers(1, 5, n).astype(float)
        bands = rng.integers(0, 4, n)
        cap = float(rng.integers(1, 600))
        got = oracle.priority_alloc(cap, wants, weights, bands)
        assert got.sum() <= cap + 1e-9
        assert (got <= wants + 1e-12).all()
        assert (got >= -1e-12).all()


def test_group_cap_binds():
    # Two resources, each capacity 100, sharing a group cap of 120.
    wants = [np.full(4, 50.0), np.full(4, 50.0)]
    weights = [np.ones(4), np.ones(4)]
    bands = [np.zeros(4, int), np.zeros(4, int)]
    got = oracle.grouped_priority_alloc(
        np.array([100.0, 100.0]), wants, weights, bands,
        np.array([0, 0]), np.array([120.0]),
    )
    total = sum(g.sum() for g in got)
    assert total == pytest.approx(120.0, rel=1e-6)
    # Symmetric inputs split evenly.
    np.testing.assert_allclose(got[0], got[1])


def test_uncoupled_resource_ignores_groups():
    wants = [np.full(4, 50.0), np.full(4, 50.0)]
    weights = [np.ones(4), np.ones(4)]
    bands = [np.zeros(4, int), np.zeros(4, int)]
    got = oracle.grouped_priority_alloc(
        np.array([100.0, 100.0]), wants, weights, bands,
        np.array([0, -1]), np.array([80.0]),
    )
    assert got[0].sum() == pytest.approx(80.0, rel=1e-6)
    assert got[1].sum() == pytest.approx(100.0, rel=1e-6)


def test_zero_weight_client_parity():
    """A zero-weight active client absorbs no water; the saturated
    weighted clients keep their grants (regression: the oracle's level
    finder used to collapse to 0 once weighted clients were
    exhausted)."""
    wants = np.array([60.0, 60.0])
    weights = np.array([1.0, 0.0])
    bands = np.zeros(2, int)
    got = oracle.priority_alloc(100.0, wants, weights, bands)
    np.testing.assert_allclose(got, [60.0, 0.0])
    batch = PriorityBatch(
        wants=jnp.asarray(wants)[None, :],
        weights=jnp.asarray(weights)[None, :],
        band=jnp.asarray(bands, jnp.int32)[None, :],
        active=jnp.ones((1, 2), bool),
        capacity=jnp.asarray([100.0]),
        group=jnp.asarray([-1], jnp.int32),
        group_cap=jnp.zeros(0),
    )
    np.testing.assert_allclose(
        np.asarray(solve_priority(batch, num_bands=1))[0], [60.0, 0.0]
    )
    # All weights zero: nobody can be served in overload.
    got = oracle.priority_alloc(100.0, wants, np.zeros(2), bands)
    np.testing.assert_allclose(got, [0.0, 0.0])


def test_no_groups_configured():
    """group_cap of shape [0] (the base case) must not crash and must
    equal the per-resource banded allocation."""
    rng = np.random.default_rng(4)
    active, wants, weights, band, capacity, _, _ = _random_case(rng)
    R = len(capacity)
    batch = PriorityBatch(
        wants=jnp.asarray(wants), weights=jnp.asarray(weights),
        band=jnp.asarray(band), active=jnp.asarray(active),
        capacity=jnp.asarray(capacity),
        group=jnp.full(R, -1, jnp.int32),
        group_cap=jnp.zeros(0),
    )
    got = np.asarray(solve_priority(batch, num_bands=4))
    for r in range(R):
        np.testing.assert_allclose(
            got[r, active[r]],
            oracle.priority_alloc(
                capacity[r], wants[r, active[r]], weights[r, active[r]],
                band[r, active[r]],
            ),
            rtol=1e-9, atol=1e-6,
        )


def test_heavily_overcapped_group_f32():
    """theta* far below the f32 bisection's absolute granularity: the
    multiplicative refinement must keep the group at its cap to f32
    relative precision (regression for the 32-iteration f32 path)."""
    wants = np.full((1, 4), 2.5e5, np.float32)
    batch = PriorityBatch(
        wants=jnp.asarray(wants),
        weights=jnp.ones((1, 4), jnp.float32),
        band=jnp.zeros((1, 4), jnp.int32),
        active=jnp.ones((1, 4), bool),
        capacity=jnp.asarray([1e6], jnp.float32),
        group=jnp.asarray([0], jnp.int32),
        group_cap=jnp.asarray([1e-2], jnp.float32),
    )
    got = np.asarray(solve_priority(batch, num_bands=1))
    assert got.sum() == pytest.approx(1e-2, rel=1e-4)


# ---------------------------------------------------------------- parity

def _random_case(rng, R=12, K=32, G=3, num_bands=4):
    active = np.zeros((R, K), bool)
    for r in range(R):
        active[r, : rng.integers(1, K + 1)] = True
    wants = (rng.integers(0, 100, (R, K)) * active).astype(np.float64)
    weights = (rng.integers(1, 4, (R, K)) * active).astype(np.float64)
    band = (rng.integers(0, num_bands, (R, K)) * active).astype(np.int32)
    capacity = rng.integers(50, 800, R).astype(np.float64)
    group = rng.choice(np.arange(-1, G), R).astype(np.int32)
    group_cap = rng.integers(100, 1200, G).astype(np.float64)
    return active, wants, weights, band, capacity, group, group_cap


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_jax_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    active, wants, weights, band, capacity, group, group_cap = _random_case(
        rng
    )
    batch = PriorityBatch(
        wants=jnp.asarray(wants),
        weights=jnp.asarray(weights),
        band=jnp.asarray(band),
        active=jnp.asarray(active),
        capacity=jnp.asarray(capacity),
        group=jnp.asarray(group),
        group_cap=jnp.asarray(group_cap),
    )
    got = np.asarray(solve_priority(batch, num_bands=4))

    expected_rows = oracle.grouped_priority_alloc(
        capacity,
        [wants[r, active[r]] for r in range(len(capacity))],
        [weights[r, active[r]] for r in range(len(capacity))],
        [band[r, active[r]] for r in range(len(capacity))],
        group,
        group_cap,
    )
    for r in range(len(capacity)):
        np.testing.assert_allclose(
            got[r, active[r]], expected_rows[r], rtol=1e-9, atol=1e-6,
            err_msg=f"resource {r}",
        )
    assert (got[~active] == 0).all()


def test_jax_group_caps_respected():
    rng = np.random.default_rng(9)
    active, wants, weights, band, capacity, group, group_cap = _random_case(
        rng, R=20, G=4
    )
    batch = PriorityBatch(
        wants=jnp.asarray(wants), weights=jnp.asarray(weights),
        band=jnp.asarray(band), active=jnp.asarray(active),
        capacity=jnp.asarray(capacity), group=jnp.asarray(group),
        group_cap=jnp.asarray(group_cap),
    )
    got = np.asarray(solve_priority(batch, num_bands=4))
    per_resource = got.sum(axis=1)
    for g in range(len(group_cap)):
        usage = per_resource[group == g].sum()
        assert usage <= group_cap[g] * (1 + 1e-9) + 1e-6
    assert (per_resource <= capacity + 1e-6).all()
