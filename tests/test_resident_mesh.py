"""Mesh-sharded resident ticks vs the single-device resident ticks.

The resident solvers with `mesh=` shard the device tables' row axis
over the 8-device virtual CPU mesh (tests/conftest.py forces it); the
contract is BYTE-IDENTICAL store contents versus the single-device
solver over multi-tick churn — assignments, releases, new clients,
learning-mode flips, rotation — including wide resources whose chunks
STRADDLE a shard boundary.  The narrow solver is row-local, so that is
automatic; for the wide solver it is the bit-stable psum reduction
(parallel.sharded.resident_chunk_reduces) doing the work: psum
assembles the global per-row totals from disjoint shard supports
(exact) and every shard runs the same sorted segment op, so the
straddling chunks' totals never re-associate.

World-building and churn come from the existing single-device resident
suites, so the mesh path is exercised against exactly the scenarios
they pin.
"""

import asyncio
import time

import numpy as np
import pytest

import tests.conftest  # noqa: F401

import jax

from doorman_tpu import native
from doorman_tpu.parallel import make_mesh
from doorman_tpu.parallel.mesh import make_mesh_from_spec
from doorman_tpu.solver.resident import ResidentDenseSolver
from doorman_tpu.solver.resident_wide import WideResidentSolver
from tests.test_resident_solver import (
    all_leases,
    churn,
    make_world,
)
from tests.test_resident_wide import make_world as make_wide_world

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

CHUNK_W = 8  # 21 clients/resource -> 3 chunks: every resource straddles


def assert_identical(a, b, msg=""):
    assert a.keys() == b.keys(), f"membership diverged {msg}"
    for key in a:
        assert a[key] == b[key], f"{msg} lease {key}: {a[key]} != {b[key]}"


def run_churn(solver_mesh, res_m, solver_one, res_one, ticks=8,
              check_each=True, quiesce=0, clock_box=None):
    """Drive both worlds through the shared churn scenario (plus a
    learning-mode flip at tick 4), then `quiesce` further quiet ticks;
    compare stores per tick (rotate=1) or only at the end."""
    rng_m, rng_o = (np.random.default_rng(99) for _ in range(2))
    for step in range(ticks):
        churn(res_m, step, rng_m)
        churn(res_one, step, rng_o)
        if step == 4:
            res_m[2].learning_mode_end = clock_box[0] + 100
            res_one[2].learning_mode_end = clock_box[0] + 100
        epoch = 1 if step >= 4 else 0
        solver_mesh.step(res_m, config_epoch=epoch)
        solver_one.step(res_one, config_epoch=epoch)
        if check_each:
            assert_identical(
                all_leases(res_m), all_leases(res_one), f"tick {step}"
            )
        clock_box[0] += 1.0
    for step in range(quiesce):
        solver_mesh.step(res_m, config_epoch=1)
        solver_one.step(res_one, config_epoch=1)
        clock_box[0] += 1.0
    assert_identical(all_leases(res_m), all_leases(res_one), "final")


def test_narrow_mesh_bit_identical_over_churn():
    t = [1000.0]
    clock = lambda: t[0]
    eng_m, res_m = make_world(clock)
    eng_o, res_o = make_world(clock)
    mesh = make_mesh()
    run_churn(
        ResidentDenseSolver(
            eng_m, dtype=np.float64, clock=clock, rotate_ticks=1,
            mesh=mesh,
        ),
        res_m,
        ResidentDenseSolver(
            eng_o, dtype=np.float64, clock=clock, rotate_ticks=1
        ),
        res_o,
        clock_box=t,
    )


def test_wide_mesh_bit_identical_with_straddling_chunks():
    t = [1000.0]
    clock = lambda: t[0]
    eng_m, res_m = make_wide_world(clock)
    eng_o, res_o = make_wide_world(clock)
    mesh = make_mesh()
    sm = WideResidentSolver(
        eng_m, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=CHUNK_W, mesh=mesh,
    )
    so = WideResidentSolver(
        eng_o, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=CHUNK_W,
    )
    run_churn(sm, res_m, so, res_o, clock_box=t)
    # The layout actually straddles: 4 resources x 3 chunks over 8
    # shards of 2 rows — resource 0's chunks span shards 0 and 1, etc.
    assert sm._Rp == 16 and sm._R == 12
    assert sm._Rp // sm._meshrows.n_dev == 2


def test_wide_mesh_two_axis_mesh_matches():
    """A ('dc', 'clients') 2x4 mesh flattens to the same row partition;
    the psum/pmax just run over two axes."""
    t = [1000.0]
    clock = lambda: t[0]
    eng_m, res_m = make_wide_world(clock)
    eng_o, res_o = make_wide_world(clock)
    mesh = make_mesh([2, 4], ("dc", "clients"))
    sm = WideResidentSolver(
        eng_m, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=CHUNK_W, mesh=mesh,
    )
    so = WideResidentSolver(
        eng_o, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=CHUNK_W,
    )
    run_churn(sm, res_m, so, res_o, ticks=5, clock_box=t)


def test_rotation_converges_to_single_device_fixpoint():
    """rotate_ticks>1: the mesh rotates PER SHARD (balanced delivery),
    so mid-churn store contents may transiently differ from the
    single-device solver's global rotation — a row lands a tick earlier
    on one or the other. The churn here is wants-only (bulk_refresh,
    like client refreshes whose demand moved): the device tables then
    evolve identically on both solvers, and once churn stops and both
    complete two full rotations, every store row holds the same device
    fixpoint, byte for byte (the invariant the idle fast path relies
    on). Full-assign churn that echoes the store's `has` back would
    genuinely couple the worlds to their delivery schedules — that
    feedback is pinned bit-identical at rotate_ticks=1 above, which is
    how the server runs when same-tick freshness matters."""
    t = [1000.0]
    clock = lambda: t[0]
    eng_m, res_m = make_wide_world(clock)
    eng_o, res_o = make_wide_world(clock)
    mesh = make_mesh()
    sm = WideResidentSolver(
        eng_m, dtype=np.float64, clock=clock, rotate_ticks=3,
        chunk_width=CHUNK_W, mesh=mesh,
    )
    so = WideResidentSolver(
        eng_o, dtype=np.float64, clock=clock, rotate_ticks=3,
        chunk_width=CHUNK_W,
    )

    def wants_churn(engine, resources, step, rng):
        res = resources[step % len(resources)]
        i = resources.index(res)
        engine.bulk_refresh(
            np.array([res.store._rid], np.int32),
            np.array([engine.client_handle(f"c{i}_0")], np.int64),
            np.array([t[0] + 60.0]),
            np.array([5.0]),
            np.array([float(rng.integers(1, 200))]),
        )

    rng_m, rng_o = (np.random.default_rng(7) for _ in range(2))
    for step in range(6):
        wants_churn(eng_m, res_m, step, rng_m)
        wants_churn(eng_o, res_o, step, rng_o)
        sm.step(res_m)
        so.step(res_o)
        t[0] += 1.0
    for _ in range(9):  # three full rotations, no churn
        sm.step(res_m)
        so.step(res_o)
        # The actual mesh invariant at any rotation: the device tables
        # of record are BYTE-identical every tick (the solve is over
        # the full table regardless of what delivers).
        np.testing.assert_array_equal(
            np.asarray(sm._has), np.asarray(so._has)
        )
        t[0] += 1.0
    # Store rows carry each schedule's last-delivery VINTAGE: the has
    # chain contracts to its fixpoint over the quiet rotations (here
    # exactly, after ~6 quiet ticks) but the idle fast path freezes
    # deliveries after two quiet rotations, so a row delivered a tick
    # apart on the two schedules may keep a 1-ulp-older iterate.
    # Equality bound = one contraction step of the chain (~eps * has).
    a, b = all_leases(res_m), all_leases(res_o)
    assert a.keys() == b.keys()
    for key in a:
        np.testing.assert_allclose(
            a[key], b[key], rtol=1e-13, atol=0,
            err_msg=f"fixpoint lease {key}",
        )


def test_mesh_rotation_is_balanced_across_shards():
    """Each quiet tick's delivery set spreads over the shards instead
    of marching one contiguous window through them: no shard delivers
    more than ceil(its rows / rotate) rotation rows."""
    t = [1000.0]
    clock = lambda: t[0]
    eng, res = make_wide_world(clock)
    mesh = make_mesh()
    solver = WideResidentSolver(
        eng, dtype=np.float64, clock=clock, rotate_ticks=2,
        chunk_width=CHUNK_W, mesh=mesh,
    )
    solver.step(res)  # rebuild tick delivers everything
    handle = solver.dispatch(res)
    assert handle.shard_counts is not None
    # 12 real rows over shards of 2 -> 6 populated shards; rotate=2
    # delivers 1 row per populated shard per tick.
    assert int(handle.shard_counts.max()) <= 1 + 1  # rotation + dirty
    assert (handle.shard_counts[:6] >= 1).all()
    solver.collect(handle)


def test_shard_traffic_gauges_published():
    """Mesh ticks publish per-shard byte gauges and a skew ratio in the
    default registry (scraped at /metrics, mirrored to /debug/traces
    when the tracer is on)."""
    from doorman_tpu.obs import metrics as metrics_mod

    t = [1000.0]
    clock = lambda: t[0]
    eng, res = make_wide_world(clock)
    solver = WideResidentSolver(
        eng, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=CHUNK_W, mesh=make_mesh(),
    )
    solver.step(res)
    reg = metrics_mod.default_registry()
    per = reg.gauge(
        "doorman_tick_shard_bytes",
        "Per-shard host-link payload bytes of the last mesh-sharded "
        "tick (direction: upload/download).",
        labels=("component", "direction", "shard"),
    )
    skew = reg.gauge(
        "doorman_tick_shard_skew",
        "max/mean ratio of per-shard payload bytes for the last "
        "mesh-sharded tick (1.0 = perfectly balanced).",
        labels=("component", "direction"),
    )
    # The rebuild tick delivered every row: shard 0 downloaded bytes.
    assert per.value("resident_wide", "download", "0") > 0
    assert skew.value("resident_wide", "download") >= 1.0


def test_mesh_spec_parsing():
    devices = jax.devices()
    m = make_mesh_from_spec("auto")
    assert int(np.prod(list(m.shape.values()))) == len(devices)
    m = make_mesh_from_spec("2x4")
    assert dict(m.shape) == {"dc": 2, "clients": 4}
    m = make_mesh_from_spec("8")
    assert dict(m.shape) == {"clients": 8}
    with pytest.raises(ValueError):
        make_mesh_from_spec("2xbanana")
    with pytest.raises(ValueError):
        make_mesh_from_spec("3x5")  # does not cover 8 devices


def test_server_mesh_matches_single_device_server():
    """End-to-end server wiring: a batch+native CapacityServer with
    mesh= produces byte-identical store contents to an unmeshed one
    over the same ticks — narrow resources on the narrow resident
    solver, a wide (past the patched cap) resource on the chunked one."""
    import doorman_tpu.solver.batch as batch_mod
    import doorman_tpu.solver.resident as resident_mod
    import doorman_tpu.solver.resident_wide as wide_mod
    from doorman_tpu.proto import doorman_pb2 as pb
    from doorman_tpu.server.config import parse_yaml_config
    from doorman_tpu.server.election import TrivialElection
    from doorman_tpu.server.server import CapacityServer

    config = """
resources:
- identifier_glob: "wide"
  capacity: 1000
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 500
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""

    async def body():
        servers = []
        for mesh in (make_mesh(), None):
            server = CapacityServer(
                f"srv_{'mesh' if mesh is not None else 'one'}",
                TrivialElection(), mode="batch", tick_interval=3600.0,
                minimum_refresh_interval=0.0, native_store=True,
                mesh=mesh,
            )
            await server.start(0, host="127.0.0.1")
            await server.load_config(parse_yaml_config(config))
            servers.append(server)
            # Same demand on both: gRPC-shaped decides + a bulk block
            # that pushes "wide" past the patched dense cap.
            from doorman_tpu.algorithms import Request

            for i in range(8):
                server._decide("narrow", Request(f"n{i}", 0.0, 7.0, 1))
            engine = server._store_factory.__self__
            res = server.resources
            wide = server.get_or_create_resource("wide")
            n = 40
            rids = np.full(n, wide.store._rid, np.int32)
            cids = np.array(
                [engine.client_handle(f"w{i}") for i in range(n)],
                np.int64,
            )
            engine.bulk_assign(
                rids, cids, np.full(n, time.time() + 60.0),
                np.full(n, 1.0), np.zeros(n),
                np.arange(1.0, n + 1.0), np.ones(n, np.int32),
            )
        mesh_srv, one_srv = servers
        assert mesh_srv.status()["mesh"] == {"clients": 8}
        assert one_srv.status()["mesh"] is None
        for _ in range(4):
            await mesh_srv.tick_once()
            await one_srv.tick_once()
        for rid in ("narrow", "wide"):
            a = dict(mesh_srv.resources[rid].store.items())
            b = dict(one_srv.resources[rid].store.items())
            assert a.keys() == b.keys()
            for key in a:
                assert (
                    a[key].has, a[key].wants
                ) == (b[key].has, b[key].wants), (rid, key)
        assert mesh_srv._resident is not None
        assert mesh_srv._resident_wide is not None
        assert "wide" in mesh_srv._wide_ids
        for s in servers:
            await s.stop()

    def patch(mod, cap=16):
        orig = mod.DENSE_MAX_K
        mod.DENSE_MAX_K = cap
        return orig

    mods = (batch_mod, resident_mod, wide_mod)
    origs = [patch(m) for m in mods]
    try:
        asyncio.run(body())
    finally:
        for m, o in zip(mods, origs):
            m.DENSE_MAX_K = o
