"""Dense bucketed solver parity: must match the edge-list kernel and the
numpy oracles bit-for-bit on the same tables."""

import numpy as np
import pytest

import tests.conftest  # noqa: F401
import jax.numpy as jnp

from doorman_tpu.solver.dense import DenseBatch, solve_dense
from doorman_tpu.solver import solve_tick
from tests.test_solver_kernels import build_batch, oracle_for
from tests.test_sharded import random_tables


def dense_from_tables(tables, K, dtype=np.float64):
    R = len(tables)
    wants = np.zeros((R, K), dtype)
    has = np.zeros((R, K), dtype)
    sub = np.zeros((R, K), dtype)
    active = np.zeros((R, K), dtype=bool)
    for r, t in enumerate(tables):
        n = len(t["wants"])
        wants[r, :n] = t["wants"]
        has[r, :n] = t.get("has", [0.0] * n)
        sub[r, :n] = t.get("sub", [1.0] * n)
        active[r, :n] = True
    return DenseBatch(
        wants=jnp.array(wants),
        has=jnp.array(has),
        subclients=jnp.array(sub),
        active=jnp.array(active),
        capacity=jnp.array([t["capacity"] for t in tables], dtype=dtype),
        algo_kind=jnp.array(
            np.array([int(t["kind"]) for t in tables], dtype=np.int32)
        ),
        learning=jnp.array(
            np.array([t.get("learning", False) for t in tables])
        ),
        static_capacity=jnp.array(
            np.array([t.get("static_cap", 0.0) for t in tables], dtype=dtype)
        ),
    )


@pytest.mark.parametrize("seed", range(3))
def test_dense_matches_oracles_bitwise(seed):
    tables = random_tables(seed, n_resources=20, max_clients=30)
    batch = dense_from_tables(tables, K=32)
    gets = np.asarray(solve_dense(batch))
    for r, t in enumerate(tables):
        n = len(t["wants"])
        np.testing.assert_array_equal(
            gets[r, :n], oracle_for(t), err_msg=f"resource {r} kind={t['kind']}"
        )
        assert np.all(gets[r, n:] == 0.0)


def test_dense_matches_edge_list_kernel():
    tables = random_tables(9, n_resources=16, max_clients=20)
    batch = dense_from_tables(tables, K=32)
    dense_gets = np.asarray(solve_dense(batch))
    edges, resources = build_batch(tables)
    edge_gets = np.asarray(solve_tick(edges, resources))
    i = 0
    for r, t in enumerate(tables):
        n = len(t["wants"])
        np.testing.assert_array_equal(dense_gets[r, :n], edge_gets[i : i + n])
        i += n
