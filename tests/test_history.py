"""Durable flight-record history (obs/history.py): ring + decimated
tiers, checksummed segments with torn-tail tolerance, restart-spanning
run deltas, the /debug/history route, and the cmd.obs round trips.

The decimation-boundary and torn-tail tests are the contract pins from
the PR-17 acceptance: bucket edges are exact (bucket b of factor F
covers hseq in [b*F, (b+1)*F), the partial tail stays pending), and a
half-written final line after a crash costs exactly the torn records —
never the segment, never the store.
"""

import asyncio
import json
import os
import urllib.request

import tests.conftest  # noqa: F401

from doorman_tpu.obs.history import SEGMENT_PREFIX, HistoryStore


def mk(dir=None, **kw):
    kw.setdefault("ring", 64)
    kw.setdefault("tiers", (5,))
    kw.setdefault("clock", lambda: 1000.0)
    return HistoryStore(dir, **kw)


def segs(d):
    return sorted(
        n for n in os.listdir(d)
        if n.startswith(SEGMENT_PREFIX) and n.endswith(".log")
    )


# ---------------------------------------------------------------------
# in-memory: ring + tiers
# ---------------------------------------------------------------------


def test_append_stamps_hseq_and_run_and_ring_wraps():
    hs = mk(ring=4)
    for i in range(6):
        assert hs.append({"v": i}) == i + 1
    recs = hs.records()
    # Ring holds the most recent 4, each stamped with hseq and run.
    assert [r["v"] for r in recs] == [2, 3, 4, 5]
    assert [r["hseq"] for r in recs] == [3, 4, 5, 6]
    assert all(r["run"] == 1 for r in recs)
    assert hs.head_hseq == 6


def test_tier_bucket_boundaries_are_exact():
    """Bucket b of factor F aggregates exactly hseq in [b*F, (b+1)*F),
    and every aggregate matches a sequential host recomputation."""
    hs = mk(tiers=(5,))
    values = [float(i * i % 17) for i in range(23)]
    for v in values:
        hs.append({"v": v})
    by_hseq = {i + 1: values[i] for i in range(len(values))}
    buckets = hs.records(tier=5)
    # hseq runs 1..23: bucket starts 0 (hseq 1-4), 5, 10, 15 are
    # finalized; the partial tail (hseq 20-23) stays pending.
    assert [b["hseq"] for b in buckets] == [0, 5, 10, 15]
    for b in buckets:
        members = [
            by_hseq[h]
            for h in range(b["hseq"], b["hseq"] + 5)
            if h in by_hseq
        ]
        assert b["n"] == len(members)
        f = b["fields"]["v"]
        assert f["min"] == min(members)
        assert f["max"] == max(members)
        assert f["last"] == members[-1]
        # Sequential sum/n — the same association order _TierBucket
        # accumulated in, so equality is exact, not approximate.
        acc = 0.0
        for m in members:
            acc += m
        assert f["mean"] == acc / len(members)


def test_partial_tail_emits_only_when_next_bucket_opens():
    hs = mk(tiers=(5,))
    for i in range(9):  # hseq 1..9: bucket 0 complete, bucket 5 partial
        hs.append({"v": float(i)})
    assert [b["hseq"] for b in hs.records(tier=5)] == [0]
    hs.append({"v": 9.0})  # hseq 10 opens bucket 10 -> bucket 5 emits
    assert [b["hseq"] for b in hs.records(tier=5)] == [0, 5]


def test_records_range_and_projection():
    hs = mk()
    for i in range(10):
        hs.append({"v": i, "w": -i})
    rows = hs.records(start=4, end=6, fields=["v"])
    assert [r["hseq"] for r in rows] == [4, 5, 6]
    assert all(set(r) == {"hseq", "run", "v"} for r in rows)


def test_series_reads_raw_and_tier_aggregates():
    hs = mk(tiers=(5,))
    for i in range(10):
        hs.append({"v": float(i)})
    assert hs.series("v") == [float(i) for i in range(10)]
    # hseq starts at 1: bucket 0 covers hseq 1-4 (values 0..3), bucket 5
    # covers hseq 5-9 (values 4..8); the tail (hseq 10) stays pending.
    assert hs.series("v", tier=5, agg="max") == [3.0, 8.0]
    assert hs.series("v", tier=5, agg="mean") == [1.5, 6.0]
    assert hs.series("missing") == []


# ---------------------------------------------------------------------
# durability: segments, torn tails, runs
# ---------------------------------------------------------------------


def test_reopen_replays_and_bumps_run(tmp_path):
    d = str(tmp_path)
    hs = mk(d)
    for i in range(5):
        hs.append({"v": i})
    hs.close()
    again = mk(d)
    assert [r["v"] for r in again.records()] == [0, 1, 2, 3, 4]
    assert again.run == 2
    assert again.head_hseq == 5
    # Appends continue the hseq line in a FRESH segment (a torn tail
    # is never appended to).
    before = segs(d)
    again.append({"v": 5})
    assert again.records()[-1]["hseq"] == 6
    assert len(segs(d)) == len(before) + 1
    again.close()


def test_torn_tail_costs_only_the_torn_record(tmp_path):
    d = str(tmp_path)
    hs = mk(d)
    for i in range(5):
        hs.append({"v": i})
    hs.close()
    path = os.path.join(d, segs(d)[0])
    lines = open(path, "rb").readlines()
    # A crash mid-write: the final line is half there.
    with open(path, "wb") as f:
        f.writelines(lines[:-1])
        f.write(lines[-1][: len(lines[-1]) // 2])
    again = mk(d)
    assert [r["v"] for r in again.records()] == [0, 1, 2, 3]
    assert again.run == 2
    again.close()


def test_corruption_stops_replay_of_that_segment_only(tmp_path):
    d = str(tmp_path)
    hs = mk(d)
    for i in range(3):
        hs.append({"v": i})
    hs.close()
    # Run 2 writes its own segment.
    hs2 = mk(d)
    hs2.append({"v": 100})
    hs2.close()
    first, second = segs(d)[:2]
    path = os.path.join(d, first)
    lines = open(path, "rb").readlines()
    lines[1] = b"xxxxxxxx corrupted-line\n"  # bit rot mid-segment
    open(path, "wb").writelines(lines)
    again = mk(d)
    # Segment 1 replays only up to the corruption; segment 2 is intact.
    assert [r["v"] for r in again.records()] == [0, 100]
    assert again.run == 3
    again.close()


def test_segment_rotation_and_retention(tmp_path):
    d = str(tmp_path)
    hs = mk(d, segment_records=2, max_segments=2)
    for i in range(12):
        hs.append({"v": i})
    hs.close()
    assert len(segs(d)) <= 3  # cap + the in-progress segment


def test_run_delta_spans_restarts(tmp_path):
    d = str(tmp_path)
    hs = mk(d)
    for _ in range(5):
        hs.append({"wall_ms": 10.0})
    assert hs.run_delta("wall_ms") is None  # one run: no delta yet
    hs.close()
    again = mk(d)
    for _ in range(5):
        again.append({"wall_ms": 20.0})
    delta = again.run_delta("wall_ms")
    assert delta is not None
    assert delta["run"] == 2 and delta["previous_run"] == 1
    assert delta["current"] == 20.0 and delta["previous"] == 10.0
    assert delta["delta"] == 10.0 and delta["ratio"] == 2.0
    assert delta["samples"] == 5 and delta["previous_samples"] == 5
    again.close()


def test_runs_and_status(tmp_path):
    d = str(tmp_path)
    hs = mk(d)
    hs.append({"v": 1})
    hs.close()
    again = mk(d)
    again.append({"v": 2})
    assert again.runs() == [1, 2]
    st = again.status()
    assert st["run"] == 2 and st["segments"] == 2
    assert st["ring"] == 2 and st["head_hseq"] == 2
    again.close()


def test_append_never_raises_on_disk_trouble(tmp_path):
    d = str(tmp_path)
    hs = mk(d)
    hs.append({"v": 1})
    # Yank the directory out from under the store: the tick loop's
    # appends must keep working in-memory.
    hs.close()
    for n in segs(d):
        os.remove(os.path.join(d, n))
    os.rmdir(d)
    assert hs.append({"v": 2}) == 2
    assert [r["v"] for r in hs.records()] == [1, 2]


# ---------------------------------------------------------------------
# export surfaces
# ---------------------------------------------------------------------


def test_view_and_chrome_export():
    hs = mk()
    for i in range(3):
        hs.append({"v": i, "wall_ms": 1.0 + i})
    view = hs.view(fields=["v"])
    assert view["run"] == 1 and view["tier"] == 0
    assert [r["v"] for r in view["records"]] == [0, 1, 2]
    trace = json.loads(hs.chrome())
    assert trace["traceEvents"], "chrome export is empty"


# ---------------------------------------------------------------------
# cmd.obs round trips
# ---------------------------------------------------------------------


def _obs(args_list, out_path):
    from doorman_tpu.cmd.obs import make_parser, run

    args = make_parser().parse_args(args_list + ["--out", str(out_path)])
    rc = run(args)
    return rc, out_path.read_text() if out_path.exists() else ""


def test_cmd_obs_round_trips(tmp_path):
    d = str(tmp_path / "hist")
    hs = mk(d)
    for i in range(7):
        hs.append({"wall_ms": 5.0 + i, "tick": i})
    hs.close()
    hs2 = mk(d)
    for i in range(7):
        hs2.append({"wall_ms": 9.0 + i, "tick": i})
    hs2.close()

    rc, text = _obs(["status", "--history-dir", d], tmp_path / "s.json")
    assert rc == 0
    st = json.loads(text)
    assert st["runs"] == [1, 2] and st["segments"] == 2

    rc, text = _obs(
        ["query", "--history-dir", d, "--start", "3", "--end", "5",
         "--field", "wall_ms"],
        tmp_path / "q.json",
    )
    assert rc == 0
    view = json.loads(text)
    assert [r["hseq"] for r in view["records"]] == [3, 4, 5]
    assert all("wall_ms" in r for r in view["records"])

    rc, text = _obs(
        ["delta", "--history-dir", d, "--field", "wall_ms"],
        tmp_path / "d.json",
    )
    assert rc == 0
    delta = json.loads(text)
    assert delta["run"] == 2 and delta["previous_run"] == 1
    assert delta["delta"] == 4.0

    rc, text = _obs(["export", "--history-dir", d], tmp_path / "t.json")
    assert rc == 0
    assert json.loads(text)["traceEvents"]

    rc, text = _obs(
        ["detect", "--history-dir", d, "--field", "wall_ms"],
        tmp_path / "a.json",
    )
    assert rc == 0
    report = json.loads(text)
    assert set(report) == {"anomalies", "detections", "per_field"}


def test_cmd_obs_delta_needs_two_runs(tmp_path):
    d = str(tmp_path / "hist")
    hs = mk(d)
    hs.append({"wall_ms": 5.0})
    hs.close()
    rc, text = _obs(
        ["delta", "--history-dir", d, "--field", "wall_ms"],
        tmp_path / "d.json",
    )
    assert rc == 1
    assert "error" in json.loads(text)


# ---------------------------------------------------------------------
# the live server: /debug/history and restart-spanning SLO windows
# ---------------------------------------------------------------------

CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""


def _fetch(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read().decode()


async def _run_server_ticks(history_dir, ticks, *, debug_probe=None):
    from doorman_tpu.server.config import parse_yaml_config
    from doorman_tpu.server.election import TrivialElection
    from doorman_tpu.server.server import CapacityServer

    server = CapacityServer(
        "hist-server", TrivialElection(), mode="batch",
        minimum_refresh_interval=0.0, history_dir=history_dir,
        audit_sample=2, audit_inline=True, detect=True,
    )
    port = await server.start(0, host="127.0.0.1")
    await server.load_config(parse_yaml_config(CONFIG))
    await asyncio.sleep(0)
    from doorman_tpu.client import Client

    client = await Client.connect(
        f"127.0.0.1:{port}", "client-1", minimum_refresh_interval=0.0
    )
    await client.resource("r0", wants=40)
    for _ in range(ticks):
        await server.tick_once()
        await client.refresh_once()
    out = {}
    if debug_probe is not None:
        out = await debug_probe(server)
    verdicts = server.evaluate_slos()
    samples = len(server.history.series("wall_ms"))
    run = server.history.run
    delta = server.history.run_delta("wall_ms")
    await client.close()
    await server.stop()
    return {
        "verdicts": verdicts,
        "samples": samples,
        "run": run,
        "delta": delta,
        **out,
    }


def test_server_history_survives_restart_and_feeds_slos(tmp_path):
    d = str(tmp_path / "server-hist")
    first = asyncio.run(_run_server_ticks(d, 6))
    assert first["run"] == 1 and first["samples"] >= 6
    assert first["delta"] is None
    second = asyncio.run(_run_server_ticks(d, 6))
    # Generation 2 sees both lifetimes: the SLO window and the
    # trajectory delta span the restart.
    assert second["run"] == 2
    # The window holds run 1's samples PLUS this generation's: strictly
    # more than either lifetime alone could supply.
    assert second["samples"] >= first["samples"] + 6
    assert second["delta"] is not None
    assert second["delta"]["run"] == 2
    assert second["delta"]["previous_run"] == 1
    # The audit gate rode along and stayed clean.
    by_name = {v["slo"]: v for v in second["verdicts"]}
    assert by_name["audit_divergence"]["status"] == "pass"
    assert by_name["detector_anomalies"]["status"] in ("pass", "fail")


def test_debug_history_route(tmp_path):
    from doorman_tpu.obs import DebugServer, Registry

    async def probe(server):
        debug = DebugServer(host="127.0.0.1", registry=Registry())
        debug.add_server(server, asyncio.get_running_loop())
        dport = debug.start()
        loop = asyncio.get_running_loop()
        try:
            status, text = await loop.run_in_executor(
                None, _fetch, dport, "/debug/history?format=json"
            )
            assert status == 200
            body = json.loads(text)
            view = body["hist-server"]
            assert view["run"] == 1
            assert len(view["records"]) == 4
            assert all("wall_ms" in r for r in view["records"])

            status, text = await loop.run_in_executor(
                None, _fetch, dport,
                "/debug/history?format=json&start=2&end=3",
            )
            assert [
                r["hseq"] for r in json.loads(text)["hist-server"]["records"]
            ] == [2, 3]

            status, text = await loop.run_in_executor(
                None, _fetch, dport, "/debug/history?format=chrome"
            )
            assert status == 200 and json.loads(text)["traceEvents"]

            status, text = await loop.run_in_executor(
                None, _fetch, dport, "/debug/history"
            )
            assert status == 200 and "hist-server" in text
        finally:
            debug.stop()
        return {}

    asyncio.run(_run_server_ticks(str(tmp_path / "h"), 4, debug_probe=probe))
