"""Streaming lease push (WatchCapacity): the parity pin + the contract.

The parity pin (ISSUE 9): for any churn schedule, the lease sequence a
streaming client observes must be byte-identical to the change-filtered
sequence the same client would read by polling every tick. The harness
runs TWO identically-configured servers on one virtual clock — the
"poll side" serves a client that polls after every tick, the "stream
side" serves the same client as a WatchCapacity subscriber — drives an
identical churn schedule against both, and compares serialized
ResourceResponse rows: every pushed row must equal, byte for byte, the
poll row of the same tick, and the pushed sequence must be exactly the
polls' changed-subsequence (capacity filter). Runs over the Python and
native store engines (the native side exercises the resident tick's
device-extracted delta set), with a mid-run mastership flip and a
disconnect + resume-from-seq reconnect.

Contract tests: admission AIMD shed + per-band stream caps on
establishment (RESOURCE_EXHAUSTED + retry-after trailing metadata),
UNIMPLEMENTED poll fallback when stream push is off, the quiet-stream
expiry-margin safety poll, slow-consumer reset, and seq monotonicity.
"""

import asyncio

import grpc
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.client import Client
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto import doorman_stream_pb2 as spb
from doorman_tpu.proto.grpc_api import CapacityStub
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

CONFIG = """
resources:
- identifier_glob: prop
  capacity: 100
  safe_capacity: 3
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 80
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""

RESOURCES = ("prop", "fair")
# Watcher priorities per resource: mixed bands on one stream.
WATCH_PRIO = {"prop": 2, "fair": 0}
# (tick, churner, resource, wants) — the shared schedule. Ticks 6 and
# 10/12 are the flip and the disconnect window (see the parity test).
CHURN = [
    (1, "c1", "prop", 70.0),
    (2, "c2", "fair", 55.0),
    (3, "c1", "prop", 20.0),
    (3, "c2", "fair", 90.0),
    (5, "c3", "prop", 40.0),
    (8, "c1", "prop", 75.0),
    (9, "c2", "fair", 10.0),
    (11, "c3", "prop", 5.0),
    (13, "c1", "prop", 60.0),
]
TOTAL_TICKS = 15
FLIP_TICK = 6
DISCONNECT_TICK = 10
RECONNECT_TICK = 12


def run(coro):
    return asyncio.run(coro)


class StreamReader:
    """Reads a WatchCapacity stream without the wait_for(call.read())
    trap: cancelling a pending read cancels the whole RPC, so the
    pending read task is kept across timeouts instead."""

    def __init__(self, call):
        self.call = call
        self._pending = None

    async def read(self, timeout=5.0):
        if self._pending is None:
            self._pending = asyncio.ensure_future(self.call.read())
        done, _ = await asyncio.wait({self._pending}, timeout=timeout)
        if not done:
            return None
        task, self._pending = self._pending, None
        return task.result()

    async def read_exactly(self, n, timeout=5.0):
        out = []
        for _ in range(n):
            msg = await self.read(timeout)
            assert msg is not None and msg is not grpc.aio.EOF, (
                f"expected {n} pushed messages, got {len(out)}"
            )
            out.append(msg)
        return out

    def cancel(self):
        if self._pending is not None:
            self._pending.cancel()
            self._pending = None
        self.call.cancel()


async def make_server(clock, *, native_store, stream_push,
                      tick_interval=1.0, config_yaml=CONFIG, **kwargs):
    server = CapacityServer(
        "srv", TrivialElection(), mode="batch",
        tick_interval=tick_interval, minimum_refresh_interval=0.0,
        clock=clock, native_store=native_store, stream_push=stream_push,
        **kwargs,
    )
    port = await server.start(0, host="127.0.0.1")
    await server.load_config(parse_yaml_config(config_yaml))
    await asyncio.sleep(0)  # election callbacks land
    server.current_master = f"127.0.0.1:{port}"
    # The harness owns the tick cadence.
    for task in server._tasks:
        task.cancel()
    server._tasks.clear()
    return server, f"127.0.0.1:{port}"


def watch_request(client_id, leases, resume_seq=0):
    req = spb.WatchCapacityRequest(client_id=client_id,
                                   resume_seq=resume_seq)
    for rid in RESOURCES:
        rr = req.resource.add()
        rr.resource_id = rid
        rr.priority = WATCH_PRIO[rid]
        rr.wants = 30.0
        if leases.get(rid) is not None:
            rr.has.CopyFrom(leases[rid])
    return req


class PollSide:
    """The watcher as a poll-every-tick client (raw stub, has carried)."""

    def __init__(self, stub):
        self.stub = stub
        self.leases = {}
        # resource -> list of serialized changed rows, filtered by what
        # a client OBSERVES of a lease: (capacity, safe_capacity,
        # refresh_interval) — expiry advances every poll by design and
        # is excluded (it is exactly what the push path saves).
        self.changed = {rid: [] for rid in RESOURCES}
        self.keys = {}  # resource -> last observed key
        self.rows = {}  # resource -> latest serialized row

    async def poll(self, record=True):
        req = pb.GetCapacityRequest(client_id="w")
        for rid in RESOURCES:
            rr = req.resource.add()
            rr.resource_id = rid
            rr.priority = WATCH_PRIO[rid]
            rr.wants = 30.0
            if self.leases.get(rid) is not None:
                rr.has.CopyFrom(self.leases[rid])
        out = await self.stub.GetCapacity(req)
        assert not out.HasField("mastership"), "unexpected redirect"
        polled = {}
        for row in out.response:
            rid = row.resource_id
            key = (
                row.gets.capacity, row.safe_capacity,
                row.gets.refresh_interval,
            )
            if record and key != self.keys.get(rid):
                self.changed[rid].append(row.SerializeToString())
            self.keys[rid] = key
            self.rows[rid] = row.SerializeToString()
            lease = pb.Lease()
            lease.CopyFrom(row.gets)
            self.leases[rid] = lease
            polled[rid] = row
        return polled


async def drive_churn(tick, stubs, leases_by_stub):
    """Apply this tick's churn rows identically against every server."""
    for at, cid, rid, wants in CHURN:
        if at != tick:
            continue
        for stub in stubs:
            leases = leases_by_stub[id(stub)]
            req = pb.GetCapacityRequest(client_id=cid)
            rr = req.resource.add()
            rr.resource_id = rid
            rr.priority = 1
            rr.wants = wants
            if leases.get((cid, rid)) is not None:
                rr.has.CopyFrom(leases[(cid, rid)])
            out = await stub.GetCapacity(req)
            lease = pb.Lease()
            lease.CopyFrom(out.response[0].gets)
            leases[(cid, rid)] = lease


async def reregister_after_flip(tick, stubs, leases_by_stub):
    """A flip wipes all state; every churner re-reports its wants (the
    reference's wipe-and-relearn contract), in identical order."""
    current = {}
    for at, cid, rid, wants in CHURN:
        if at < tick:
            current[(cid, rid)] = wants
    for stub in stubs:
        leases = leases_by_stub[id(stub)]
        for (cid, rid), wants in sorted(current.items()):
            req = pb.GetCapacityRequest(client_id=cid)
            rr = req.resource.add()
            rr.resource_id = rid
            rr.priority = 1
            rr.wants = wants
            if leases.get((cid, rid)) is not None:
                rr.has.CopyFrom(leases[(cid, rid)])
            out = await stub.GetCapacity(req)
            lease = pb.Lease()
            lease.CopyFrom(out.response[0].gets)
            leases[(cid, rid)] = lease


@pytest.mark.parametrize(
    "native_store",
    [
        False,
        pytest.param(
            True,
            marks=pytest.mark.skipif(
                not native.native_available(),
                reason="native engine unavailable",
            ),
        ),
    ],
    ids=["python-store", "native-store"],
)
def test_push_poll_parity(native_store):
    """The parity pin: pushed rows == the polls' changed-subsequence,
    byte for byte, across churn, a mastership flip, and a
    resume-from-seq reconnect (Python + native stores, mixed bands)."""

    async def body():
        t = [1000.0]
        clock = lambda: t[0]  # noqa: E731
        pserver, paddr = await make_server(
            clock, native_store=native_store, stream_push=False
        )
        sserver, saddr = await make_server(
            clock, native_store=native_store, stream_push=True
        )
        pch = grpc.aio.insecure_channel(paddr)
        sch = grpc.aio.insecure_channel(saddr)
        try:
            pstub, sstub = CapacityStub(pch), CapacityStub(sch)
            churn_leases = {id(pstub): {}, id(sstub): {}}
            poll = PollSide(pstub)

            # Establishment at t0: first poll on the poll side, stream
            # snapshot on the stream side — byte-identical full rows.
            await poll.poll()
            stream_leases = {}
            last_seq = 0
            pushed = {rid: [] for rid in RESOURCES}

            def apply_push(msg):
                nonlocal last_seq
                assert msg.seq > last_seq or msg.snapshot
                last_seq = int(msg.seq)
                for row in msg.response:
                    pushed[row.resource_id].append(row.SerializeToString())
                    lease = pb.Lease()
                    lease.CopyFrom(row.gets)
                    stream_leases[row.resource_id] = lease

            reader = StreamReader(
                sstub.WatchCapacity(watch_request("w", stream_leases))
            )
            snap = await reader.read()
            assert snap.snapshot
            assert sorted(r.resource_id for r in snap.response) == sorted(
                RESOURCES
            )
            apply_push(snap)
            for rid in RESOURCES:
                assert pushed[rid] == poll.changed[rid], rid

            registry = sserver._streams

            async def stream_tick():
                before = registry.total_messages
                await sserver.tick_once()
                for msg in await reader.read_exactly(
                    registry.total_messages - before
                ):
                    apply_push(msg)

            disconnected = False
            for tick in range(1, TOTAL_TICKS):
                if tick == FLIP_TICK:
                    # Mid-stream mastership flip on both sides: the
                    # stream must end with a terminal redirect; the
                    # subscriber re-establishes with its resume token.
                    await pserver._on_is_master(False)
                    await sserver._on_is_master(False)
                    term = await reader.read()
                    assert term.HasField("mastership")
                    reader.cancel()
                    await pserver._on_is_master(True)
                    await sserver._on_is_master(True)
                    await reregister_after_flip(
                        tick, (pstub, sstub), churn_leases
                    )
                    # Poll side: one poll (this is the tick's poll);
                    # stream side: re-establish with the resume token.
                    before = registry.total_messages
                    reader = StreamReader(sstub.WatchCapacity(
                        watch_request("w", stream_leases,
                                      resume_seq=last_seq)
                    ))
                    resumed = await reader.read()
                    assert resumed.snapshot
                    apply_push(resumed)
                    await poll.poll()
                    for rid in RESOURCES:
                        assert pushed[rid] == poll.changed[rid], (
                            f"flip parity broke for {rid}"
                        )
                    continue
                if tick == DISCONNECT_TICK:
                    # Drop the stream (no release — the subscription
                    # just vanishes); churn keeps landing on both sides.
                    reader.cancel()
                    await asyncio.sleep(0.05)  # server sees the cancel
                    disconnected = True
                if tick == RECONNECT_TICK:
                    disconnected = False
                await drive_churn(tick, (pstub, sstub), churn_leases)
                t[0] += 1.0
                await pserver.tick_once()
                if disconnected:
                    await sserver.tick_once()
                    await poll.poll()
                    continue
                if tick == RECONNECT_TICK:
                    await sserver.tick_once()
                    await poll.poll()
                    # Resume-from-seq reconnect: the first message must
                    # carry exactly the net-changed rows, each byte-
                    # identical to this tick's poll row.
                    reader = StreamReader(sstub.WatchCapacity(
                        watch_request("w", stream_leases,
                                      resume_seq=last_seq)
                    ))
                    resumed = await reader.read()
                    assert resumed.snapshot
                    for row in resumed.response:
                        assert (
                            row.SerializeToString()
                            == poll.rows[row.resource_id]
                        ), f"resume row for {row.resource_id} diverged"
                        assert (
                            row.gets.capacity
                            != stream_leases[row.resource_id].capacity
                        ), "resume pushed an unchanged row"
                    # Rebase the filtered sequences across the gap: the
                    # stream legitimately never observed intra-gap
                    # flapping, so both sides restart from the resumed
                    # state.
                    apply_push(resumed)
                    for rid in RESOURCES:
                        poll.changed[rid] = list(pushed[rid])
                    continue
                await stream_tick()
                await poll.poll()
                for rid in RESOURCES:
                    assert pushed[rid] == poll.changed[rid], (
                        f"parity broke for {rid} at tick {tick}: "
                        f"{len(pushed[rid])} pushed vs "
                        f"{len(poll.changed[rid])} polled changes"
                    )

            # The schedule must have exercised real pushes (not a
            # vacuous run) and the dedup (fewer pushes than ticks).
            total = sum(len(v) for v in pushed.values())
            assert total >= 6, f"schedule produced only {total} changes"
            assert total < TOTAL_TICKS * len(RESOURCES)
            reader.cancel()
        finally:
            await pch.close()
            await sch.close()
            await pserver.stop()
            await sserver.stop()

    run(body())


def test_stream_cap_and_admission_shed():
    """Per-band stream caps and the AIMD gate both refuse establishment
    with RESOURCE_EXHAUSTED + a doorman-retry-after trailing hint; a
    different band is unaffected by another band's cap."""

    async def body():
        from doorman_tpu.admission import Admission

        t = [500.0]
        server, addr = await make_server(
            lambda: t[0], native_store=False, stream_push=True,
            max_streams_per_band=1,
            admission=Admission(coalesce_window=0.0),
        )
        ch = grpc.aio.insecure_channel(addr)
        try:
            stub = CapacityStub(ch)

            def req(cid, prio, resume=0):
                r = spb.WatchCapacityRequest(client_id=cid)
                rr = r.resource.add()
                rr.resource_id = "prop"
                rr.priority = prio
                rr.wants = 10.0
                return r

            r1 = StreamReader(stub.WatchCapacity(req("a", 0)))
            assert (await r1.read()).snapshot
            # Same band: capped.
            r2 = StreamReader(stub.WatchCapacity(req("b", 0)))
            with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                await r2.read()
            e = excinfo.value
            assert e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            hints = [
                float(v) for k, v in (e.trailing_metadata() or ())
                if k == "doorman-retry-after"
            ]
            assert hints and hints[0] > 0
            # Another band: admitted.
            r3 = StreamReader(stub.WatchCapacity(req("c", 1)))
            assert (await r3.read()).snapshot
            # The AIMD gate sheds establishment once the level drops
            # (band 0 extinguishes first while band 1 exists).
            server._admission.controller.level = 0.01
            r4 = StreamReader(stub.WatchCapacity(req("d", 0)))
            with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                await r4.read()
            assert (
                excinfo.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            )
            tallies = server._admission.tallies
            assert any(
                m == "WatchCapacity" and c["shed"] > 0
                for (m, _b), c in tallies.items()
            )
            assert server.status()["streams"]["by_band"] == {
                "0": 1, "1": 1,
            }
            r1.cancel()
            r3.cancel()
        finally:
            await ch.close()
            await server.stop()

    run(body())


def test_unimplemented_falls_back_to_poll():
    """A stream-mode client against a server WITHOUT stream push keeps
    working: WatchCapacity answers UNIMPLEMENTED and the client's poll
    fallback serves capacity exactly as before."""

    async def body():
        server, addr = await make_server(
            lambda: __import__("time").time(),
            native_store=False, stream_push=False, tick_interval=0.05,
        )
        # The harness cancelled the tick loop; restart it for this
        # real-time test.
        server._tasks.append(asyncio.create_task(server._tick_loop()))
        try:
            client = await Client.connect(
                addr, "w", stream=True, minimum_refresh_interval=0.0
            )
            res = await client.resource("prop", 25.0)
            value = await asyncio.wait_for(res.capacity().get(), 10)
            assert value == 25.0
            # The stream probe backed off instead of spinning.
            assert client._stream_retry_at > client._clock()
            await client.close()
        finally:
            await server.stop()

    run(body())


SHORT_LEASE_CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 5, refresh_interval: 1,
              learning_mode_duration: 0}
"""


def test_quiet_stream_polls_only_at_expiry_margin():
    """The steady-state contract both ways: a healthy-but-quiet stream
    is trusted PAST the refresh interval — no poll, which is the whole
    RPC reduction — and degrades to the safety poll once quiet reaches
    the lease-expiry margin (expiry - refresh_interval), so the lease
    is re-observed before it can lapse even if the stream died without
    an error. Checkpoints anchor to the granted lease's actual expiry
    so loaded boxes don't turn the margins into flakes."""

    async def body():
        import time as _time

        server, addr = await make_server(
            _time.time, native_store=False, stream_push=True,
            tick_interval=0.05, config_yaml=SHORT_LEASE_CONFIG,
        )
        server._tasks.append(asyncio.create_task(server._tick_loop()))
        polls = []
        orig = server.on_request
        server.on_request = lambda m, d, e: (
            polls.append(m) if m == "GetCapacity" else None,
            orig(m, d, e),
        )
        try:
            client = await Client.connect(
                addr, "w", stream=True, minimum_refresh_interval=0.0
            )
            res = await client.resource("prop", 25.0)
            await asyncio.wait_for(res.capacity().get(), 10)
            baseline = len(polls)
            # Quiet for SEVERAL refresh intervals (1s each, lease 5s):
            # a polling client would have refreshed repeatedly; the
            # stream client must not until the expiry margin. Check
            # 2s before expiry — a full second clear of the margin
            # poll due at expiry - refresh_interval.
            expiry = float(res.lease.expiry_time)
            await asyncio.sleep(max(0.0, expiry - 2.0 - _time.time()))
            assert len(polls) == baseline, (
                "stream polled while the lease had margin"
            )
            # Quiet INTO the margin: the safety poll fires (due at
            # expiry-1), and the healthy stream stays open through it.
            await asyncio.sleep(max(0.0, expiry + 1.5 - _time.time()))
            assert len(polls) > baseline, (
                "no safety poll at the lease-expiry margin"
            )
            assert len(server._streams) == 1, "the quiet stream was dropped"
            await client.close()
        finally:
            await server.stop()

    run(body())


def test_stream_storm_driver():
    """loadtest.storm --stream: workers hold WatchCapacity streams and
    count pushes; establishments beyond the per-band cap are shed with
    retry-after, honored before re-establishing."""

    async def body():
        import time as _time

        from doorman_tpu.loadtest.storm import run_storm

        server, addr = await make_server(
            _time.time, native_store=False, stream_push=True,
            tick_interval=0.05, max_streams_per_band=2,
        )
        server._tasks.append(asyncio.create_task(server._tick_loop()))
        try:
            out = await run_storm(
                addr, "prop", workers=6, duration=1.5, bands=(0, 1),
                wants=5.0, stream=True, seed=7,
            )
            # 3 workers per band against a cap of 2: some establish
            # (each opening snapshot is a push), the extras shed.
            assert out["ok"] >= 2, out
            assert out["pushes"] >= out["ok"], out
            assert out["shed"] >= 1 and out["shed_by_band"], out
            assert out["errors"] == 0, out
        finally:
            await server.stop()

    run(body())


def test_slow_consumer_reset():
    """A subscription whose queue overflows is terminated with a
    redirect-to-self (resume beats dropping deltas) — and the reset is
    confined to its own shard: streams on other shards are untouched."""

    async def body():
        from doorman_tpu.server.streams import QUEUE_SIZE, Subscription

        t = [100.0]
        server, addr = await make_server(
            lambda: t[0], native_store=False, stream_push=True,
            stream_shards=4,
        )
        try:
            registry = server._streams
            shard = registry.shard_of("c")
            other = registry.shards[(shard.index + 1) % 4]
            sub = Subscription("c", 0, {"prop": (10.0, 0)},
                               shard=shard.index)
            shard._subs[sub] = None
            bystander = Subscription("d", 0, {"prop": (10.0, 0)},
                                     shard=other.index)
            other._subs[bystander] = None
            for _ in range(QUEUE_SIZE + 4):
                shard.enqueue(sub, shard._message_bytes([]), 0)
            assert sub.terminated
            assert shard.total_resets == 1
            assert registry.total_resets == 1
            assert other.total_resets == 0
            assert not bystander.terminated
            # The last queued message is the terminal redirect (a
            # message object; data pushes are pre-serialized bytes).
            last = None
            while not sub.queue.empty():
                last = sub.queue.get_nowait()
            assert last is not None and not isinstance(last, bytes)
            assert last.HasField("mastership")
        finally:
            await server.stop()

    run(body())


def test_seq_stamped_from_persist_journal():
    """With persistence configured, pushed seqs ride the journal's
    sequence numbers: strictly increasing and never below the journal
    position that recorded the push's decides."""

    async def body():
        from doorman_tpu.persist import PersistManager
        from doorman_tpu.persist.backend import MemoryBackend

        t = [2000.0]
        clock = lambda: t[0]  # noqa: E731
        server, addr = await make_server(
            clock, native_store=False, stream_push=True,
            persist=PersistManager(
                MemoryBackend(), snapshot_interval=1e9,
                flush_interval=1.0, clock=clock,
            ),
        )
        ch = grpc.aio.insecure_channel(addr)
        try:
            stub = CapacityStub(ch)
            req = spb.WatchCapacityRequest(client_id="w")
            rr = req.resource.add()
            rr.resource_id = "prop"
            rr.wants = 30.0
            reader = StreamReader(stub.WatchCapacity(req))
            msgs = [await reader.read()]
            # Churn from another client forces pushes.
            other = {}
            seqs = [msgs[0].seq]
            for wants in (90.0, 150.0, 40.0):
                creq = pb.GetCapacityRequest(client_id="c")
                crr = creq.resource.add()
                crr.resource_id = "prop"
                crr.wants = wants
                if other.get("prop") is not None:
                    crr.has.CopyFrom(other["prop"])
                out = await stub.GetCapacity(creq)
                lease = pb.Lease()
                lease.CopyFrom(out.response[0].gets)
                other["prop"] = lease
                before = server._streams.total_messages
                t[0] += 1.0
                await server.tick_once()
                t[0] += 1.0
                await server.tick_once()
                for msg in await reader.read_exactly(
                    server._streams.total_messages - before
                ):
                    seqs.append(msg.seq)
            assert len(seqs) >= 3
            assert all(b > a for a, b in zip(seqs, seqs[1:])), seqs
            assert seqs[-1] >= server._persist.journal.seq - 2
            reader.cancel()
        finally:
            await ch.close()
            await server.stop()

    run(body())


@pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)
def test_delta_filter_limits_fanout_decides():
    """With the resident delta tracking live and refresh intervals
    longer than the tick, quiet ticks run ZERO fanout decides and a
    one-resource churn only re-decides that resource's subscribers —
    the 1M-subscriber scaling argument, observable at small scale."""

    async def body():
        t = [3000.0]
        clock = lambda: t[0]  # noqa: E731
        config = parse_yaml_config(
            "resources:\n"
            "- identifier_glob: \"*\"\n"
            "  capacity: 100\n"
            "  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 600,\n"
            "              refresh_interval: 30,\n"
            "              learning_mode_duration: 0}\n"
        )
        server = CapacityServer(
            "srv", TrivialElection(), mode="batch", tick_interval=1.0,
            minimum_refresh_interval=0.0, clock=clock,
            native_store=True, stream_push=True,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(config)
        await asyncio.sleep(0)
        server.current_master = f"127.0.0.1:{port}"
        for task in server._tasks:
            task.cancel()
        server._tasks.clear()
        ch = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        try:
            stub = CapacityStub(ch)
            readers = []
            for i, rid in enumerate(("ra", "rb", "rc")):
                req = spb.WatchCapacityRequest(client_id=f"w{i}")
                rr = req.resource.add()
                rr.resource_id = rid
                rr.wants = 30.0
                reader = StreamReader(stub.WatchCapacity(req))
                assert (await reader.read()).snapshot
                readers.append(reader)

            decides = []
            orig = server._decide
            server._decide = lambda rid, request: (
                decides.append(rid), orig(rid, request)
            )[1]
            # Warm ticks: deliveries converge, then quiet ticks decide
            # nothing (refresh_interval 30 >> tick 1).
            for _ in range(4):
                t[0] += 1.0
                await server.tick_once()
            decides.clear()
            for _ in range(3):
                t[0] += 1.0
                await server.tick_once()
            assert decides == [], f"quiet ticks decided: {decides}"
            # Churn one resource: only its subscriber re-decides.
            creq = pb.GetCapacityRequest(client_id="x")
            crr = creq.resource.add()
            crr.resource_id = "rb"
            crr.wants = 500.0
            await stub.GetCapacity(creq)
            decides.clear()
            for _ in range(2):
                t[0] += 1.0
                await server.tick_once()
            assert set(decides) == {"rb"}, decides
            for reader in readers:
                reader.cancel()
        finally:
            await ch.close()
            await server.stop()

    run(body())


# ---------------------------------------------------------------------------
# Sharded fan-out engine (ISSUE 12)
# ---------------------------------------------------------------------------


def test_shard_distribution_stability():
    """The shard route is the federation router's stable blake2b hash —
    a cross-process contract, pinned by value — and it spreads client
    ids evenly enough that no shard holds a pathological share."""
    from collections import Counter

    from doorman_tpu.federation.router import stable_shard

    assert [stable_shard(f"w{i}", 4) for i in range(8)] == [
        3, 0, 2, 1, 2, 2, 3, 2,
    ]
    assert [stable_shard(f"client-{i}", 8) for i in range(8)] == [
        7, 6, 6, 2, 7, 1, 4, 5,
    ]
    counts = Counter(stable_shard(f"c{i}", 8) for i in range(1000))
    assert len(counts) == 8
    assert max(counts.values()) < 2 * min(counts.values())


def _watch_req(client_id, resources, prio_of, wants=30.0):
    req = spb.WatchCapacityRequest(client_id=client_id)
    for rid in resources:
        rr = req.resource.add()
        rr.resource_id = rid
        rr.priority = prio_of(rid)
        rr.wants = wants
    return req


def _drain_queue(sub):
    """Drain one subscription queue into parsed messages (data pushes
    are pre-serialized bytes; terminals are message objects)."""
    out = []
    while not sub.queue.empty():
        item = sub.queue.get_nowait()
        if isinstance(item, (bytes, bytearray)):
            item = spb.WatchCapacityResponse.FromString(bytes(item))
        out.append(item)
    return out


@pytest.mark.parametrize(
    "native_store",
    [
        False,
        pytest.param(
            True,
            marks=pytest.mark.skipif(
                not native.native_available(),
                reason="native engine unavailable",
            ),
        ),
    ],
    ids=["python-store", "native-store"],
)
def test_sharded_parity_with_single_shard(native_store):
    """The sharding pin: for the same churn schedule and watcher set,
    every watcher's pushed row sequence on a 4-shard registry is
    byte-identical to the single-shard path, and the per-tick sum of
    per-shard outbound (messages / delta rows / bytes) matches the
    unsharded fanout exactly — across mixed bands, a mid-run mastership
    flip, and a slow-consumer reset confined to one shard."""

    async def body():
        from doorman_tpu.algorithms import Request

        t = [4000.0]
        clock = lambda: t[0]  # noqa: E731
        servers = {}
        for name, shards in (("one", 1), ("four", 4)):
            server, _addr = await make_server(
                clock, native_store=native_store, stream_push=True,
                stream_shards=shards, flightrec_capacity=0,
            )
            servers[name] = server
        watchers = [f"w{i}" for i in range(6)]  # spread: shards 3,0,2,1,2,2
        prio = {"prop": 2, "fair": 0}
        subs = {name: {} for name in servers}
        pushed = {name: {w: [] for w in watchers} for name in servers}

        def establish(name, w, resume=False):
            server = servers[name]
            req = _watch_req(w, RESOURCES, lambda r: prio[r])
            sub = server._streams.subscribe(req)
            server._stream_match_add(sub)
            subs[name][w] = sub

        def drain(name):
            for w, sub in subs[name].items():
                for msg in _drain_queue(sub):
                    for row in msg.response:
                        pushed[name][w].append(
                            (row.resource_id, row.SerializeToString())
                        )

        def churn(tick):
            for at, cid, rid, wants in CHURN:
                if at != tick:
                    continue
                for server in servers.values():
                    server._decide(
                        rid, Request(cid, 0.0, wants, 1, priority=1)
                    )

        try:
            for name in servers:
                for w in watchers:
                    establish(name, w)
            for name in servers:
                drain(name)
            assert pushed["four"] == pushed["one"]

            for tick in range(1, TOTAL_TICKS):
                if tick == FLIP_TICK:
                    for name, server in servers.items():
                        await server._on_is_master(False)
                        for sub in subs[name].values():
                            terms = _drain_queue(sub)
                            assert terms and terms[-1].HasField(
                                "mastership"
                            )
                        await server._on_is_master(True)
                        for w in watchers:
                            establish(name, w)
                    churn(tick)
                    continue
                churn(tick)
                t[0] += 1.0
                totals = {}
                for name, server in servers.items():
                    await server.tick_once()
                    totals[name] = server._streams.take_tick_stats()
                    drain(name)
                # Sigma per-shard outbound == the unsharded fanout,
                # every tick.
                for key in ("messages", "deltas_pushed", "push_bytes"):
                    assert totals["four"][key] == totals["one"][key], (
                        f"tick {tick}: {key} diverged: {totals}"
                    )
                assert totals["four"]["stream_shards"] == 4
                for w in watchers:
                    assert pushed["four"][w] == pushed["one"][w], (
                        f"tick {tick}: watcher {w} push sequence diverged"
                    )
            total = sum(len(v) for v in pushed["one"].values())
            assert total >= 6, f"schedule produced only {total} pushes"

            # Slow-consumer reset stays confined to its shard: overflow
            # w0's queue; every other watcher's stream survives.
            from doorman_tpu.server.streams import QUEUE_SIZE

            registry = servers["four"]._streams
            victim = subs["four"]["w0"]
            shard = registry.shards[victim.shard]
            for _ in range(QUEUE_SIZE + 2):
                shard.enqueue(victim, shard._message_bytes([]), 0)
            assert victim.terminated
            assert registry.total_resets == 1
            for w in watchers[1:]:
                assert not subs["four"][w].terminated
        finally:
            for server in servers.values():
                await server.stop()

    run(body())


@pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)
def test_quiet_tick_walks_zero_subscriptions():
    """The quiet-tick pin: with delta tracking live, refresh intervals
    longer than the tick, and nothing changed, the fanout walks ZERO
    subscriptions (not merely zero decides — the deadline wheel
    short-circuits the per-subscriber scan entirely), and the due
    refresh beat still fires on schedule."""

    async def body():
        t = [5000.0]
        clock = lambda: t[0]  # noqa: E731
        config = parse_yaml_config(
            "resources:\n"
            "- identifier_glob: \"*\"\n"
            "  capacity: 100\n"
            "  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 600,\n"
            "              refresh_interval: 30,\n"
            "              learning_mode_duration: 0}\n"
        )
        server = CapacityServer(
            "srv", TrivialElection(), mode="batch", tick_interval=1.0,
            minimum_refresh_interval=0.0, clock=clock,
            native_store=True, stream_push=True, stream_shards=2,
            flightrec_capacity=0,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(config)
        await asyncio.sleep(0)
        server.current_master = f"127.0.0.1:{port}"
        for task in server._tasks:
            task.cancel()
        server._tasks.clear()
        try:
            registry = server._streams
            subs = []
            for i, rid in enumerate(("ra", "rb", "rc")):
                req = _watch_req(f"w{i}", (rid,), lambda r: 0)
                sub = registry.subscribe(req)
                server._stream_match_add(sub)
                subs.append(sub)
            # Warm ticks: deliveries converge.
            for _ in range(4):
                t[0] += 1.0
                await server.tick_once()
            registry.take_tick_stats()
            # Quiet ticks: zero subscriptions walked, zero pushed.
            for _ in range(3):
                t[0] += 1.0
                await server.tick_once()
                st = registry.take_tick_stats()
                assert st["subs_walked"] == 0, st
                assert st["messages"] == 0, st
                assert st["matched_pairs"] == 0, st
            assert len(registry) == 3
            # The silent-refresh beat still fires: jump past the
            # refresh interval and the wheel walks exactly the due set.
            t[0] += 31.0
            await server.tick_once()
            st = registry.take_tick_stats()
            assert st["subs_walked"] == 3, st
            # One churned resource: only its subscriber is walked (the
            # matcher's pair extraction, not a registry scan).
            from doorman_tpu.algorithms import Request

            server._decide("rb", Request("x", 0.0, 500.0, 1, priority=0))
            for _ in range(2):
                t[0] += 1.0
                await server.tick_once()
            st = registry.take_tick_stats()
            assert st["subs_walked"] == 1, st
            assert st["matched_pairs"] >= 1, st
            for sub in subs:
                _drain_queue(sub)
        finally:
            await server.stop()

    run(body())


def test_stream_storm_multiplexed():
    """loadtest.storm --streams-per-worker: one worker task holds many
    streams over one shared channel and still counts establishments,
    pushes, and sheds correctly."""

    async def body():
        import time as _time

        from doorman_tpu.loadtest.storm import run_storm

        server, addr = await make_server(
            _time.time, native_store=False, stream_push=True,
            tick_interval=0.05, max_streams_per_band=4,
            stream_shards=2,
        )
        server._tasks.append(asyncio.create_task(server._tick_loop()))
        try:
            out = await run_storm(
                addr, "prop", workers=2, duration=1.5, bands=(0, 1),
                wants=5.0, stream=True, seed=11, streams_per_worker=3,
            )
            # 2 workers x 3 streams over 2 bands against a cap of 4
            # per band: most establish, the overflow sheds with a
            # retry-after that the mux loop honors per stream.
            assert out["ok"] >= 4, out
            assert out["pushes"] >= out["ok"], out
            assert out["errors"] == 0, out
            assert server._streams.status()["shards"] == 2
        finally:
            await server.stop()

    run(body())
