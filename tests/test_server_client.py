"""Integration tests over real gRPC on loopback (capability parity with
reference server_test.go / client_test.go): mastership redirect, learning
mode, release, config hot-swap, GetServerCapacity validation, the client
refresh loop, and the batch (TPU-tick) serving mode."""

import asyncio
import time

import grpc
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.client import Client, Connection
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import (
    CapacityServicer,
    CapacityStub,
    add_capacity_servicer,
)
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

CONFIG = """
resources:
- identifier_glob: proportional
  capacity: 100
  safe_capacity: 2
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 120
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""

LEARNING_CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 100}
"""


async def make_server(mode="immediate", config=CONFIG, **kwargs):
    server = CapacityServer(
        "test-server", TrivialElection(), mode=mode,
        minimum_refresh_interval=0.0, **kwargs,
    )
    port = await server.start(0, host="127.0.0.1")
    await server.load_config(parse_yaml_config(config))
    await asyncio.sleep(0)  # let election callbacks land
    server.current_master = f"127.0.0.1:{port}"
    return server, f"127.0.0.1:{port}"


def run(coro):
    return asyncio.run(coro)


def capacity_request(client_id, resource_id, wants, has=None):
    req = pb.GetCapacityRequest(client_id=client_id)
    rr = req.resource.add()
    rr.resource_id = resource_id
    rr.wants = wants
    if has is not None:
        rr.has.CopyFrom(has)
    return req


def test_discovery():
    async def body():
        server, addr = await make_server()
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                out = await stub.Discovery(pb.DiscoveryRequest())
                assert out.is_master
                assert out.mastership.master_address == addr
        finally:
            await server.stop()

    run(body())


def test_get_capacity_immediate():
    async def body():
        server, addr = await make_server()
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                out = await stub.GetCapacity(
                    capacity_request("client-1", "proportional", 40.0)
                )
                assert len(out.response) == 1
                resp = out.response[0]
                assert resp.resource_id == "proportional"
                assert resp.gets.capacity == 40.0
                assert resp.safe_capacity == 2.0
                assert resp.gets.refresh_interval == 1
        finally:
            await server.stop()

    run(body())


def test_invalid_request_rejected():
    async def body():
        server, addr = await make_server()
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                    await stub.GetCapacity(
                        capacity_request("", "proportional", 40.0)
                    )
                assert excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                # A NUL in the id could forge a downstream server's band
                # sub-lease key; the wire rejects it.
                with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                    await stub.GetCapacity(
                        capacity_request(
                            "mid\x00band\x002", "proportional", 40.0
                        )
                    )
                assert excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                    await stub.ReleaseCapacity(
                        pb.ReleaseCapacityRequest(
                            client_id="mid\x00band\x002",
                            resource_id=["proportional"],
                        )
                    )
                assert excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        finally:
            await server.stop()

    run(body())


def test_mastership_redirect():
    async def body():
        server, addr = await make_server()

        # A fake non-master that always points at the real server
        # (mirrors reference client_test.go:117-172).
        class NonMaster(CapacityServicer):
            async def GetCapacity(self, request, context):
                out = pb.GetCapacityResponse()
                out.mastership.master_address = addr
                return out

            async def Discovery(self, request, context):
                out = pb.DiscoveryResponse(is_master=False)
                out.mastership.master_address = addr
                return out

        fake = grpc.aio.server()
        add_capacity_servicer(fake, NonMaster())
        fake_port = fake.add_insecure_port("127.0.0.1:0")
        await fake.start()
        try:
            conn = Connection(f"127.0.0.1:{fake_port}", max_retries=2)
            out = await conn.execute(
                lambda stub: stub.GetCapacity(
                    capacity_request("client-1", "proportional", 10.0)
                )
            )
            assert out.response[0].gets.capacity == 10.0
            assert conn.current_master == addr
            await conn.close()
        finally:
            await fake.stop(None)
            await server.stop()

    run(body())


def test_learning_mode_and_post_learning_clamp():
    async def body():
        server, addr = await make_server(config=LEARNING_CONFIG)
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                # During learning mode the server grants whatever the client
                # reports it has (even over capacity).
                has = pb.Lease(expiry_time=2**31, refresh_interval=1,
                               capacity=300.0)
                out = await stub.GetCapacity(
                    capacity_request("c1", "proportional", 300.0, has)
                )
                assert out.response[0].gets.capacity == 300.0

                # Leave learning mode (rewind became_master_at, like the
                # reference test rewinds it, server_test.go:339-382).
                server.became_master_at -= 10_000
                for res in server.resources.values():
                    res.learning_mode_end = 0.0

                out = await stub.GetCapacity(
                    capacity_request("c1", "proportional", 300.0, has)
                )
                assert out.response[0].gets.capacity <= 100.0
        finally:
            await server.stop()

    run(body())


def test_release_capacity():
    async def body():
        server, addr = await make_server()
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                await stub.GetCapacity(
                    capacity_request("c1", "proportional", 40.0)
                )
                assert server.resources["proportional"].store.has_client("c1")
                out = await stub.ReleaseCapacity(
                    pb.ReleaseCapacityRequest(
                        client_id="c1", resource_id=["proportional", "ghost"]
                    )
                )
                assert not out.HasField("mastership")
                assert not server.resources["proportional"].store.has_client(
                    "c1"
                )
        finally:
            await server.stop()

    run(body())


def test_config_hot_swap():
    async def body():
        server, addr = await make_server()
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                await stub.GetCapacity(capacity_request("c1", "res", 10.0))
                new_config = parse_yaml_config(
                    """
resources:
- identifier_glob: "*"
  capacity: 7
  algorithm: {kind: STATIC, lease_length: 60, refresh_interval: 1}
"""
                )
                await server.load_config(new_config)
                out = await stub.GetCapacity(
                    capacity_request("c1", "res", 10.0)
                )
                # STATIC grants min(per-client capacity, wants) = 7.
                assert out.response[0].gets.capacity == 7.0
        finally:
            await server.stop()

    run(body())


def test_get_server_capacity_and_validation():
    async def body():
        server, addr = await make_server()
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                req = pb.GetServerCapacityRequest(server_id="downstream")
                rr = req.resource.add()
                rr.resource_id = "proportional"
                band = rr.wants.add()
                band.priority = 1
                band.num_clients = 5
                band.wants = 250.0
                out = await stub.GetServerCapacity(req)
                resp = out.response[0]
                assert resp.resource_id == "proportional"
                assert resp.gets.capacity == 100.0  # whole capacity, one asker
                assert resp.algorithm.kind == pb.Algorithm.PROPORTIONAL_SHARE
                # subclients must be >= 1
                bad = pb.GetServerCapacityRequest(server_id="downstream")
                rr = bad.resource.add()
                rr.resource_id = "proportional"
                band = rr.wants.add()
                band.priority = 1
                band.num_clients = 0
                band.wants = 1.0
                with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                    await stub.GetServerCapacity(bad)
                assert excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        finally:
            await server.stop()

    run(body())


def test_batch_mode_serves_solved_grants():
    async def body():
        server, addr = await make_server(mode="batch")
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                # First round: unknown clients go through the immediate path.
                for c, w in [("a", 60.0), ("b", 60.0), ("c", 10.0)]:
                    await stub.GetCapacity(
                        capacity_request(c, "proportional", w)
                    )
                # Batched tick rebalances everyone at once.
                await server.tick_once()
                await server.tick_once()
                out = await stub.GetCapacity(
                    capacity_request("b", "proportional", 60.0)
                )
                # Solved grant: 60 * 100/130.
                assert out.response[0].gets.capacity == pytest.approx(
                    60.0 * 100.0 / 130.0
                )
        finally:
            await server.stop()

    run(body())


def test_batch_mode_native_resident_serves_solved_grants():
    """Native batch servers take the device-resident tick path: grants
    land one tick after their solve (the pipelined collect), then serve
    from the store like any batch grant."""

    async def body():
        from doorman_tpu import native

        if not native.native_available():
            pytest.skip("native engine unavailable")
        server, addr = await make_server(mode="batch", native_store=True)
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                for c, w in [("a", 60.0), ("b", 60.0), ("c", 10.0)]:
                    await stub.GetCapacity(
                        capacity_request(c, "proportional", w)
                    )
                # dispatch -> collect+dispatch -> collect lands grants.
                await server.tick_once()
                await server.tick_once()
                await server.tick_once()
                assert server._resident is not None
                assert server._resident.ticks >= 1
                out = await stub.GetCapacity(
                    capacity_request("b", "proportional", 60.0)
                )
                assert out.response[0].gets.capacity == pytest.approx(
                    60.0 * 100.0 / 130.0
                )
        finally:
            await server.stop()

    run(body())


def test_client_refresh_loop():
    async def body():
        server, addr = await make_server()
        try:
            client = await Client.connect(
                addr, "itest-client", minimum_refresh_interval=0.05
            )
            res = await client.resource("proportional", 30.0)
            capacity = await asyncio.wait_for(res.capacity().get(), timeout=5)
            assert capacity == 30.0
            # Raising wants refreshes to a bigger grant on the next cycle.
            await res.ask(80.0)
            capacity = await asyncio.wait_for(res.capacity().get(), timeout=5)
            assert capacity == 80.0
            await res.release()
            assert not server.resources["proportional"].store.has_client(
                "itest-client"
            )
            await client.close()
        finally:
            await server.stop()

    run(body())


def test_outage_expiry_falls_back_to_safe_capacity():
    """A lease expiring during a server outage falls back to the
    SERVER-SENT safe capacity (design.md semantics; reference
    simulation/client.py:197-200), not to 0 — and the QPS limiter
    throttles to that fallback rate. 0 remains the fallback only when
    the server never sent a safe capacity."""

    async def body():
        from doorman_tpu.ratelimiter import new_qps

        server, addr = await make_server()
        client = await Client.connect(
            addr, "safecap-client", minimum_refresh_interval=0.05
        )
        try:
            res = await client.resource("proportional", 30.0)
            capacity = await asyncio.wait_for(res.capacity().get(), timeout=5)
            assert capacity == 30.0
            # The config's safe_capacity rode the response in.
            assert res.safe_capacity == 2.0
            limiter = new_qps(res)
            await asyncio.sleep(0.1)  # limiter consumes the 30.0 update

            # Outage: server down, lease forced past expiry.
            await server.stop()
            res.lease.expiry_time = 1
            deadline = time.monotonic() + 5.0
            while res.lease is not None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert res.lease is None, "lease not expired during outage"
            assert res.current_capacity() == 2.0
            # The limiter now meters at the safe rate: 2 QPS -> one
            # release per 500ms; three waits take >= ~1s, not instant.
            t0 = time.monotonic()
            for _ in range(3):
                await asyncio.wait_for(limiter.wait(), timeout=5)
            assert time.monotonic() - t0 > 0.8, "limiter not throttled"
            await limiter.close()
        finally:
            await client.close()
            await server.stop()

    run(body())


def test_outage_expiry_without_safe_capacity_pushes_zero():
    """The '*' template has no safe_capacity: the server sends a
    dynamic fallback (capacity / clients) — the client must use what
    the server sent; clearing the field server-side would mean 0."""

    async def body():
        server, addr = await make_server()
        client = await Client.connect(
            addr, "nocap-client", minimum_refresh_interval=0.05
        )
        try:
            res = await client.resource("other", 10.0)
            await asyncio.wait_for(res.capacity().get(), timeout=5)
            # Dynamic safe capacity: capacity 120 / 1 client.
            assert res.safe_capacity == 120.0
            # Simulate "server never sent one" (old servers / cleared
            # field): the conservative 0 fallback applies.
            res.safe_capacity = None
            await server.stop()
            res.lease.expiry_time = 1
            deadline = time.monotonic() + 5.0
            while res.lease is not None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert res.lease is None
            assert res.current_capacity() == 0.0
        finally:
            await client.close()
            await server.stop()

    run(body())


def test_not_master_redirects_client():
    async def body():
        server, addr = await make_server()
        try:
            server.is_master = False
            server.current_master = ""
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                out = await stub.GetCapacity(
                    capacity_request("c1", "proportional", 10.0)
                )
                assert out.HasField("mastership")
                assert not out.mastership.HasField("master_address")
        finally:
            await server.stop()

    run(body())


def test_redirect_loop_between_two_non_masters_is_bounded():
    """Two servers each pointing at the other as master must not spin
    the connection in an unbounded sleepless redirect chase: after the
    bounded number of immediate redirects the attempt fails, and with
    max_retries exhausted execute() raises MasterUnknown (reference
    runMasterAware's redirect loop, connection.go:143-227)."""

    async def body():
        from doorman_tpu.client.connection import MasterUnknown

        a, addr_a = await make_server()
        b, addr_b = await make_server()
        conn = None
        try:
            a.is_master = False
            a.current_master = addr_b
            b.is_master = False
            b.current_master = addr_a
            conn = Connection(addr_a, max_retries=1)
            # wait_for makes a broken redirect bound FAIL crisply
            # instead of hanging the suite on an endless chase.
            with pytest.raises(MasterUnknown):
                await asyncio.wait_for(
                    conn.execute(
                        lambda stub: stub.GetCapacity(
                            capacity_request("c1", "proportional", 5.0)
                        )
                    ),
                    timeout=30.0,
                )
        finally:
            if conn is not None:
                await conn.close()
            await a.stop()
            await b.stop()

    run(body())
