"""Serving-plane scale-out (doorman_tpu.frontend): ring + pool pins.

Three layers of contract, each pinned here without spawning a single
process (the ring's framing logic is identical over a bytearray and a
SharedMemory block — frontend/ring.py):

  * the ring itself — frame round-trip, wrap, torn-frame tolerance
    (unpublished bytes are never read), checksum reject + resync,
    lap detection with gap accounting, fresh-reader no-replay, and the
    shared-memory backing;
  * the worker core — parking (frames before registration), the
    per-worker deadline wheel (a stream that stops seeing frames AND
    beats resets loudly — never a silent lapse), desync reset;
  * THE parity pin — a pooled server (inline frontend pool: the tick
    process publishes to rings, worker cores pump to subscribers) and
    a plain in-process server on one virtual clock, same churn, every
    watcher's pushed (seq, row) sequence byte-identical per shard —
    including across a mid-sequence worker crash + restart where the
    affected streams resume from seq with no replay and no gap.

Plus the establishment ramp's window batching and the publisher's
shard->worker reassignment contract.
"""

import asyncio

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.admission.ramp import EstablishmentRamp
from doorman_tpu.algorithms import Request
from doorman_tpu.frontend.publisher import RingPublisher
from doorman_tpu.frontend.ring import (
    CTRL_SIZE,
    HEADER_SIZE,
    KIND_BEAT,
    KIND_PUSH,
    KIND_TERMINAL,
    Ring,
    RingReader,
    RingWriter,
)
from doorman_tpu.frontend.worker import WorkerCore
from doorman_tpu.proto import doorman_pb2 as pb
from tests.test_streaming import (
    CHURN,
    RESOURCES,
    TOTAL_TICKS,
    _drain_queue,
    make_server,
    watch_request,
)

NATIVE_PARAMS = [
    False,
    pytest.param(
        True,
        marks=pytest.mark.skipif(
            not native.native_available(),
            reason="native engine unavailable",
        ),
    ),
]


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# The ring.
# ---------------------------------------------------------------------------


class TestRing:
    def test_round_trip(self):
        ring = Ring.in_memory(512)
        w = RingWriter(ring)
        r = RingReader(ring)
        w.append(3, KIND_PUSH, 42, b"hello")
        w.append(1, KIND_TERMINAL, 7, b"bye")
        w.append(0, KIND_BEAT, 0)
        res = r.poll()
        assert not res.lapped and res.corrupt == 0 and res.gap == 0
        assert [
            (f.seq, f.shard, f.kind, f.stream_id, f.payload)
            for f in res.frames
        ] == [
            (1, 3, KIND_PUSH, 42, b"hello"),
            (2, 1, KIND_TERMINAL, 7, b"bye"),
            (3, 0, KIND_BEAT, 0, b""),
        ]
        assert r.poll().frames == []  # drained

    def test_wrap(self):
        """Frames straddling the physical end split into two slices and
        reassemble byte-exact, across hundreds of logical laps."""
        ring = Ring.in_memory(256)
        w = RingWriter(ring)
        r = RingReader(ring)
        for i in range(300):
            payload = bytes([i % 251]) * (17 + i % 13)
            w.append(i % 7, KIND_PUSH, i, payload)
            res = r.poll()
            assert len(res.frames) == 1
            assert res.frames[0].payload == payload
            assert res.frames[0].seq == i + 1
            assert not res.lapped and res.corrupt == 0

    def test_torn_frame_never_read(self):
        """Bytes past the published write_pos — a writer that died
        mid-frame — are invisible: the reader stops at the control
        block's position."""
        ring = Ring.in_memory(256)
        w = RingWriter(ring)
        r = RingReader(ring)
        w.append(0, KIND_PUSH, 1, b"published")
        # Torn frame: bytes in place, control NOT published.
        ring.write_at(w.write_pos, b"\xde\xad\xbe\xef" * 10)
        res = r.poll()
        assert [f.payload for f in res.frames] == [b"published"]
        assert res.corrupt == 0

    def test_checksum_reject_resyncs(self):
        ring = Ring.in_memory(512)
        w = RingWriter(ring)
        r = RingReader(ring)
        w.append(0, KIND_PUSH, 1, b"ok-1")
        pos = w.write_pos
        w.append(0, KIND_PUSH, 2, b"victim")
        w.append(0, KIND_PUSH, 3, b"after")
        # Flip one payload byte of the middle frame in place.
        off = (pos + HEADER_SIZE) % ring.capacity
        ring.buf[CTRL_SIZE + off] ^= 0xFF
        res = r.poll()
        assert [f.payload for f in res.frames] == [b"ok-1"]
        assert res.corrupt == 1
        assert res.gap >= 1  # the victim (and the tail) accounted
        # Resynced to write_pos: new frames flow again.
        w.append(0, KIND_PUSH, 4, b"fresh")
        res = r.poll()
        assert [f.payload for f in res.frames] == [b"fresh"]
        assert res.corrupt == 0

    def test_lap_detection_counts_gap(self):
        ring = Ring.in_memory(256)
        w = RingWriter(ring)
        r = RingReader(ring)
        w.append(0, KIND_PUSH, 0, b"seen")
        assert len(r.poll().frames) == 1
        for i in range(20):  # far more than capacity: reader lapped
            w.append(0, KIND_PUSH, i, b"x" * 40)
        res = r.poll()
        assert res.lapped
        assert res.gap == 20  # every unread frame accounted, none silent
        assert res.frames == []
        w.append(0, KIND_PUSH, 99, b"recovered")
        res = r.poll()
        assert [f.payload for f in res.frames] == [b"recovered"]
        assert not res.lapped

    def test_fresh_reader_starts_at_write_pos(self):
        """A restarted worker must not replay frames: resume rides the
        push-seq contract, not ring replay."""
        ring = Ring.in_memory(512)
        w = RingWriter(ring)
        w.append(0, KIND_PUSH, 1, b"old-1")
        w.append(0, KIND_PUSH, 2, b"old-2")
        r = RingReader(ring)  # fresh cursor: at write_pos
        assert r.poll().frames == []
        w.append(0, KIND_PUSH, 3, b"new")
        res = r.poll()
        assert [f.payload for f in res.frames] == [b"new"]
        assert res.gap == 0

    def test_oversized_frame_rejected(self):
        ring = Ring.in_memory(128)
        w = RingWriter(ring)
        with pytest.raises(ValueError):
            w.append(0, KIND_PUSH, 1, b"x" * 128)

    def test_control_block_seqlock_roundtrip(self):
        """The control block is a seqlock: writes land between an
        odd/even version bump, reads see exactly what was published
        and the version rests even."""
        import struct

        ring = Ring.in_memory(512)
        ring.write_control(123, 7)
        assert ring.read_control() == (123, 7)
        ver = struct.unpack_from("<Q", ring.buf, 0)[0]
        assert ver == 2  # one publish: +1 busy, +1 published

    def test_control_read_survives_writer_death_mid_update(self):
        """A version stuck odd (writer died mid-update) must not hang
        the reader: the bounded retry falls through with the last copy
        — the crc/lap checks downstream keep it loud."""
        import struct

        ring = Ring.in_memory(512)
        ring.write_control(100, 1)
        struct.pack_into("<Q", ring.buf, 0, 5)  # odd, never clears
        assert ring.read_control() == (100, 1)

    def test_shared_memory_backing(self):
        """The same framing over a named SharedMemory block: writer in
        one mapping, reader attached through a second mapping."""
        name = "doorman-test-ring"
        ring = Ring.shared(name, 1024, create=True)
        try:
            w = RingWriter(ring)
            attached = Ring.shared(name, 1024)
            r = RingReader(attached)
            w.append(2, KIND_PUSH, 5, b"cross-mapping")
            res = r.poll()
            assert [f.payload for f in res.frames] == [b"cross-mapping"]
            attached.close()
        finally:
            ring.close()
            ring.unlink()


# ---------------------------------------------------------------------------
# Publisher routing.
# ---------------------------------------------------------------------------


class TestPublisher:
    def test_home_routing_and_reassign(self):
        p = RingPublisher(3, ring_bytes=1024)
        assert [p.shard_worker(s) for s in range(6)] == [0, 1, 2, 0, 1, 2]
        moved = p.reassign(1)
        assert moved and all(w != 1 for w in moved.values())
        assert p.shard_worker(1) != 1 and p.shard_worker(4) != 1
        # Deterministic: same dead set, same map.
        assert p.shard_worker(1) == p.shard_worker(1)
        p.revive(1)
        assert p.shard_worker(1) == 1 and p.shard_worker(4) == 1

    def test_publish_to_dead_worker_fails_loudly(self):
        p = RingPublisher(2, ring_bytes=1024)
        assert p.publish(0, 0, 1, b"live")
        p.reassign(0)
        assert not p.publish(0, 0, 1, b"dead")
        assert not p.publish_terminal(0, 0, 1, b"dead")
        assert p.publish(1, 1, 2, b"live")

    def test_beat_only_live_rings(self):
        p = RingPublisher(2, ring_bytes=1024)
        r0, r1 = RingReader(p.rings[0]), RingReader(p.rings[1])
        p.reassign(0)
        p.beat()
        assert r0.poll().frames == []
        frames = r1.poll().frames
        assert len(frames) == 1 and frames[0].kind == KIND_BEAT


# ---------------------------------------------------------------------------
# The worker core: parking + the deadline wheel.
# ---------------------------------------------------------------------------


def _make_core(ring, events, **kwargs):
    return WorkerCore(
        0, ring,
        deliver=lambda sid, h, p: events.append(("push", sid, p)),
        terminal=lambda sid, h, p: events.append(("term", sid, p)),
        on_stall=lambda sid, h, reason: events.append(
            ("stall", sid, reason)
        ),
        **kwargs,
    )


class TestWorkerCore:
    def test_parking_flushes_at_registration(self):
        ring = Ring.in_memory(1024)
        w = RingWriter(ring)
        events = []
        core = _make_core(ring, events)
        w.append(0, KIND_PUSH, 7, b"early")
        core.pump(0.0)
        assert events == []  # parked, not lost
        core.register(7, object(), 0.0)
        assert events == [("push", 7, b"early")]
        w.append(0, KIND_PUSH, 7, b"late")
        core.pump(0.0)
        assert events[-1] == ("push", 7, b"late")

    def test_parking_is_bounded_evicts_oldest(self):
        """A full park buffer evicts the OLDEST parked stream — its
        registration is the furthest overdue — so the frame arriving
        now (the stream registering next) still parks."""
        ring = Ring.in_memory(1 << 16)
        w = RingWriter(ring)
        events = []
        core = _make_core(ring, events, park_limit=4)
        for i in range(10):
            w.append(0, KIND_PUSH, 100 + i, b"x")
        core.pump(0.0)
        assert core.parked_frames == 10  # every frame parked on arrival
        assert core.parked_dropped == 6  # the 6 oldest streams evicted
        core.register(100, object(), 0.0)  # evicted: nothing to flush
        assert events == []
        core.register(109, object(), 0.0)  # newest survived
        assert events == [("push", 109, b"x")]

    def test_parked_orphans_expire_after_margin(self):
        """Frames for a stream that never registers (dropped between
        publish and the Drop RPC, cancelled establish) expire after one
        stall margin — orphans cannot permanently pin the bounded park
        buffer toward PARK_LIMIT."""
        ring = Ring.in_memory(1024)
        w = RingWriter(ring)
        events = []
        core = _make_core(ring, events, tick_interval=1.0,
                          stall_margin=3.0)
        w.append(0, KIND_PUSH, 7, b"orphan")
        core.pump(0.0)
        core.check_deadlines(2.9)
        assert core.parked_expired == 0  # inside the margin
        core.check_deadlines(3.1)
        assert core.parked_expired == 1  # reclaimed
        core.register(7, object(), 3.1)  # late registration: no flush
        assert events == []

    def test_deadline_wheel_resets_silent_streams(self):
        """No frames AND no beats for a full margin: every held stream
        resets loudly (the never-silent-lapse leg)."""
        ring = Ring.in_memory(1024)
        w = RingWriter(ring)
        events = []
        core = _make_core(ring, events, tick_interval=1.0,
                          stall_margin=3.0)
        core.register(1, object(), 0.0)
        core.register(2, object(), 0.0)
        assert core.check_deadlines(2.9) == 0  # inside the margin
        # A beat re-arms everything: the ring demonstrably flows.
        w.append(0, KIND_BEAT, 0)
        core.pump(2.9)
        assert core.check_deadlines(4.0) == 0
        # Then silence past the margin: both streams reset.
        assert core.check_deadlines(6.0) == 2
        assert sorted(e[1] for e in events if e[0] == "stall") == [1, 2]
        assert core.held() == 0

    def test_desync_resets_every_stream(self):
        ring = Ring.in_memory(256)
        w = RingWriter(ring)
        events = []
        core = _make_core(ring, events)
        core.register(1, object(), 0.0)
        for _ in range(20):  # lap the reader
            w.append(0, KIND_PUSH, 1, b"y" * 40)
        core.pump(0.0)
        assert ("stall", 1, "ring_lap") in events
        assert core.desyncs == 1 and core.held() == 0


# ---------------------------------------------------------------------------
# The establishment ramp.
# ---------------------------------------------------------------------------


class TestEstablishmentRamp:
    def test_inline_when_window_zero(self):
        async def body():
            ramp = EstablishmentRamp(window=0.0)
            out = await ramp.submit(lambda: "now")
            assert out == "now"
            assert ramp.flushes == 0  # never parked

        run(body())

    def test_window_batches_in_arrival_order(self):
        async def body():
            ramp = EstablishmentRamp(window=0.02)
            order = []

            def mk(i):
                def thunk():
                    order.append(i)
                    return i
                return thunk

            outs = await asyncio.gather(
                *[ramp.submit(mk(i)) for i in range(5)]
            )
            assert outs == [0, 1, 2, 3, 4]
            assert order == [0, 1, 2, 3, 4]  # arrival order preserved
            assert ramp.flushes == 1  # one loop callback for the burst
            assert ramp.batched == 5
            ramp.close()

        run(body())

    def test_exceptions_propagate(self):
        async def body():
            ramp = EstablishmentRamp(window=0.01)

            def boom():
                raise RuntimeError("gate exploded")

            with pytest.raises(RuntimeError, match="gate exploded"):
                await ramp.submit(boom)
            ramp.close()

        run(body())


# ---------------------------------------------------------------------------
# THE parity pin: pooled vs in-process, across a worker restart.
# ---------------------------------------------------------------------------

RESTART_TICK = 7
WORKERS = 2
SHARDS = 4


@pytest.mark.parametrize("native_store", NATIVE_PARAMS,
                         ids=["python-store", "native-store"])
def test_pooled_parity_with_in_process(native_store):
    """The tentpole pin: a pooled server (pushes ride per-worker rings
    and a worker-core pump) and a plain in-process server on one
    virtual clock produce byte-identical (seq, row) push sequences per
    watcher for the same churn — including across a mid-sequence
    worker crash + restart, where the affected streams resume from seq
    with NO replay (the has-baseline suppresses unchanged rows) and NO
    gap (per-shard seq counters continue), and per-tick outbound stats
    match exactly."""

    async def body():
        t = [4000.0]
        clock = lambda: t[0]  # noqa: E731
        plain, _ = await make_server(
            clock, native_store=native_store, stream_push=True,
            stream_shards=SHARDS, flightrec_capacity=0,
        )
        pooled, _ = await make_server(
            clock, native_store=native_store, stream_push=True,
            stream_shards=SHARDS, flightrec_capacity=0,
        )
        pool = pooled.attach_frontend(WORKERS, ring_bytes=1 << 20)
        servers = {"plain": plain, "pooled": pooled}
        watchers = [f"w{i}" for i in range(6)]
        subs = {n: {} for n in servers}
        pushed = {n: {w: [] for w in watchers} for n in servers}
        last_lease = {n: {w: {} for w in watchers} for n in servers}
        last_seq = {n: {w: 0 for w in watchers} for n in servers}

        def establish(n, w, req=None):
            server = servers[n]
            sub = server._streams.subscribe(req or watch_request(w, {}))
            server._stream_match_add(sub)
            subs[n][w] = sub

        def drain(n):
            terms = {}
            for w, sub in subs[n].items():
                for msg in _drain_queue(sub):
                    if msg.HasField("mastership"):
                        terms[w] = msg
                        continue
                    last_seq[n][w] = int(msg.seq)
                    for row in msg.response:
                        pushed[n][w].append(
                            (int(msg.seq), row.resource_id,
                             row.SerializeToString())
                        )
                        lease = pb.Lease()
                        lease.CopyFrom(row.gets)
                        last_lease[n][w][row.resource_id] = lease
            return terms

        def churn(tick):
            for at, cid, rid, wants in CHURN:
                if at != tick:
                    continue
                for server in servers.values():
                    server._decide(
                        rid, Request(cid, 0.0, wants, 1, priority=1)
                    )

        try:
            for n in servers:
                for w in watchers:
                    establish(n, w)
            # Every pooled watcher is pinned to its shard's home worker.
            for w in watchers:
                sub = subs["pooled"][w]
                assert sub.worker == sub.shard % WORKERS
                assert sub.stream_id > 0
            assert subs["plain"]["w0"].worker is None
            pool.pump_all()
            for n in servers:
                drain(n)
            assert pushed["pooled"] == pushed["plain"], (
                "establishment snapshots diverged"
            )

            for tick in range(1, TOTAL_TICKS):
                if tick == RESTART_TICK:
                    # Worker 0 dies mid-sequence. Its streams terminate
                    # with redirects (never silently); the plain server
                    # mirrors the same terminations so the per-shard
                    # seq streams stay comparable. Both sides then
                    # re-establish with resume_seq + has-baselines.
                    affected = [
                        w for w in watchers
                        if subs["pooled"][w].worker == 0
                    ]
                    assert affected, "schedule needs worker-0 streams"
                    dropped = pool.crash(0)
                    assert dropped == len(affected)
                    for w in affected:
                        plain._streams.terminate(
                            subs["plain"][w], plain._mastership()
                        )
                        plain._streams.unsubscribe(subs["plain"][w])
                        plain._stream_match_remove(subs["plain"][w])
                    terms = {n: drain(n) for n in servers}
                    for w in affected:
                        assert terms["pooled"][w].seq == (
                            terms["plain"][w].seq
                        )
                    pool.restore(0)
                    for n in servers:
                        for w in affected:
                            establish(n, w, watch_request(
                                w, last_lease[n][w],
                                resume_seq=last_seq[n][w],
                            ))
                            assert not subs[n][w].terminated
                    pool.pump_all()
                    for n in servers:
                        drain(n)
                    # Resume parity: same seqs (no gap), and the resume
                    # baseline suppressed unchanged rows (no replay).
                    assert pushed["pooled"] == pushed["plain"]
                    for w in affected:
                        assert subs["pooled"][w].worker == 0  # re-homed
                    churn(tick)
                    continue
                churn(tick)
                t[0] += 1.0
                totals = {}
                for n, server in servers.items():
                    await server.tick_once()
                    if n == "pooled":
                        pool.pump_all()
                    totals[n] = server._streams.take_tick_stats()
                    drain(n)
                for key in ("messages", "deltas_pushed", "push_bytes"):
                    assert totals["pooled"][key] == totals["plain"][key], (
                        f"tick {tick}: {key} diverged: {totals}"
                    )
                for w in watchers:
                    assert pushed["pooled"][w] == pushed["plain"][w], (
                        f"tick {tick}: watcher {w} diverged"
                    )
            total = sum(len(v) for v in pushed["plain"].values())
            assert total >= 6, f"schedule produced only {total} pushes"
            # The ring really was in the path.
            assert pool.publisher.published_frames > 0
            assert sum(c.pushes for c in pool.cores.values()) > 0
        finally:
            for server in servers.values():
                await server.stop()

    run(body())


@pytest.mark.parametrize("native_store", NATIVE_PARAMS,
                         ids=["python-store", "native-store"])
def test_worker_crash_streams_reset_to_redirect(native_store):
    """A dead worker's streams end with a mastership redirect (the
    client re-establishes, routed to a survivor) — never a silent
    lapse; surviving workers' streams are untouched."""

    async def body():
        t = [5000.0]
        clock = lambda: t[0]  # noqa: E731
        server, _ = await make_server(
            clock, native_store=native_store, stream_push=True,
            stream_shards=SHARDS, flightrec_capacity=0,
        )
        pool = server.attach_frontend(WORKERS, ring_bytes=1 << 18)
        watchers = [f"w{i}" for i in range(8)]
        subs = {}
        try:
            for w in watchers:
                sub = server._streams.subscribe(watch_request(w, {}))
                server._stream_match_add(sub)
                subs[w] = sub
            pool.pump_all()
            for sub in subs.values():
                _drain_queue(sub)
            on_w0 = [w for w in watchers if subs[w].worker == 0]
            survivors = [w for w in watchers if subs[w].worker != 0]
            assert on_w0 and survivors
            dropped = pool.crash(0)
            assert dropped == len(on_w0)
            for w in on_w0:
                msgs = _drain_queue(subs[w])
                assert msgs and msgs[-1].HasField("mastership"), (
                    f"{w}: crash must end the stream with a redirect"
                )
                assert subs[w].terminated
            for w in survivors:
                assert not subs[w].terminated
                assert _drain_queue(subs[w]) == []
            # Re-establishment lands on a survivor until restore.
            sub = server._streams.subscribe(
                watch_request(on_w0[0], {})
            )
            assert sub.worker == 1
            pool.restore(0)
            sub2 = server._streams.subscribe(watch_request("fresh", {}))
            assert sub2.worker == sub2.shard % WORKERS  # homes restored
        finally:
            await server.stop()

    run(body())


def test_ring_stall_resets_loudly_on_resume():
    """A stalled worker (pump frozen) whose ring laps resets every held
    stream on resume — redirects, not silently-missing pushes."""

    async def body():
        t = [6000.0]
        clock = lambda: t[0]  # noqa: E731
        server, _ = await make_server(
            clock, native_store=False, stream_push=True,
            stream_shards=SHARDS, flightrec_capacity=0,
        )
        # Tiny rings: a few ticks of pushes + beats lap a frozen reader.
        pool = server.attach_frontend(WORKERS, ring_bytes=512)
        watchers = [f"w{i}" for i in range(8)]
        subs = {}
        try:
            for w in watchers:
                sub = server._streams.subscribe(watch_request(w, {}))
                server._stream_match_add(sub)
                subs[w] = sub
            pool.pump_all()
            for sub in subs.values():
                _drain_queue(sub)
            pool.stall(0)
            for tick in range(6):
                for i, w in enumerate(watchers):
                    server._decide(
                        "prop",
                        Request(f"c{tick}", 0.0, 10.0 + tick + i, 1,
                                priority=1),
                    )
                t[0] += 1.0
                await server.tick_once()
                pool.pump_all()
            pool.unstall(0)
            out = pool.pump_all()
            assert out["lapped"] >= 1
            on_w0 = [w for w in watchers if subs[w].shard % WORKERS == 0]
            for w in on_w0:
                msgs = _drain_queue(subs[w])
                assert msgs and msgs[-1].HasField("mastership"), (
                    f"{w}: lap must reset the stream loudly"
                )
            for w in watchers:
                if w not in on_w0:
                    assert not subs[w].terminated
        finally:
            await server.stop()

    run(body())
