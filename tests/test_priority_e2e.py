"""PRIORITY_BANDS through the stack: config parsing/validation, the
scalar immediate-mode algorithm, and the batched tick with capacity
groups (Python and native stores)."""

import asyncio

import numpy as np
import jax
import pytest

from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.server import config as config_mod
from doorman_tpu.server.config import ConfigError
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

jax.config.update("jax_enable_x64", True)

BASE_YAML = """
groups:
  - name: upstream
    capacity: 120
resources:
  - identifier_glob: "prio-*"
    capacity: 100
    capacity_group: upstream
    algorithm:
      kind: PRIORITY_BANDS
      lease_length: 60
      refresh_interval: 5
  - identifier_glob: "*"
    capacity: 100
    algorithm:
      kind: PROPORTIONAL_SHARE
      lease_length: 60
      refresh_interval: 5
"""


# Group-free variant: immediate-mode servers REJECT grouped configs
# (group caps are enforced only by the batch tick), so the scalar
# band tests run without them.
NOGROUP_YAML = """
resources:
  - identifier_glob: "prio-*"
    capacity: 100
    algorithm:
      kind: PRIORITY_BANDS
      lease_length: 60
      refresh_interval: 5
  - identifier_glob: "*"
    capacity: 100
    algorithm:
      kind: PROPORTIONAL_SHARE
      lease_length: 60
      refresh_interval: 5
"""


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_config_groups_parse_and_validate():
    repo = config_mod.parse_yaml_config(BASE_YAML)
    assert repo.groups[0].name == "upstream"
    assert repo.resources[0].capacity_group == "upstream"
    assert repo.resources[0].algorithm.kind == pb.Algorithm.PRIORITY_BANDS

    with pytest.raises(ConfigError, match="undefined capacity group"):
        config_mod.parse_yaml_config(
            BASE_YAML.replace("name: upstream", "name: other")
        )
    with pytest.raises(ConfigError, match="requires the PRIORITY_BANDS"):
        config_mod.parse_yaml_config(
            BASE_YAML.replace("kind: PRIORITY_BANDS",
                              "kind: FAIR_SHARE")
        )
    with pytest.raises(ConfigError, match="duplicate capacity group"):
        config_mod.parse_yaml_config(BASE_YAML.replace(
            "groups:\n  - name: upstream\n    capacity: 120",
            "groups:\n  - name: upstream\n    capacity: 120\n"
            "  - name: upstream\n    capacity: 50",
        ))


def _request(client, resource, wants, priority):
    req = pb.GetCapacityRequest()
    req.client_id = client
    r = req.resource.add()
    r.resource_id = resource
    r.priority = priority
    r.wants = wants
    return req


def _make_server(clock, mode="immediate", native=False):
    server = CapacityServer(
        "s1", TrivialElection(), minimum_refresh_interval=0.0,
        clock=clock, mode=mode, native_store=native,
    )
    return server


async def _setup(server, clock):
    yaml = BASE_YAML if server.mode == "batch" else NOGROUP_YAML
    await server.load_config(config_mod.parse_yaml_config(yaml))
    await server._on_is_master(True)
    server.became_master_at = clock() - 10_000  # skip learning mode


def test_grouped_config_rejected_outside_batch_mode():
    """Group caps are enforced only by the batch tick; accepting a
    grouped config on an immediate server would validate-then-ignore it
    (silent overcommit), so load_config must reject instead."""

    async def scenario():
        clock = FakeClock()
        server = _make_server(clock, mode="immediate")
        with pytest.raises(ConfigError, match="capacity group"):
            await server.load_config(
                config_mod.parse_yaml_config(BASE_YAML)
            )
        # The server keeps running and accepts a group-free config.
        await server.load_config(
            config_mod.parse_yaml_config(NOGROUP_YAML)
        )
        # A batch server accepts the same grouped config.
        batch = _make_server(clock, mode="batch")
        await batch.load_config(config_mod.parse_yaml_config(BASE_YAML))

    asyncio.run(scenario())


def test_immediate_mode_priority_bands():
    async def scenario():
        clock = FakeClock()
        server = _make_server(clock)
        await _setup(server, clock)
        # Low-priority client asks first and gets everything...
        resp = await server.GetCapacity(
            _request("low", "prio-a", 80.0, priority=1), None
        )
        assert resp.response[0].gets.capacity == 80.0
        # ...then a high-priority client demands the full capacity. Its
        # banded share is 100, but only unpromised capacity is granted
        # immediately (the incremental discipline every scalar form
        # follows — no oversubscription while low still holds 80).
        resp = await server.GetCapacity(
            _request("high", "prio-a", 100.0, priority=5), None
        )
        assert resp.response[0].gets.capacity == 20.0
        # The low-priority client's next refresh is fully displaced...
        resp = await server.GetCapacity(
            _request("low", "prio-a", 80.0, priority=1), None
        )
        assert resp.response[0].gets.capacity == 0.0
        # ...after which the high-priority client converges to 100.
        resp = await server.GetCapacity(
            _request("high", "prio-a", 100.0, priority=5), None
        )
        assert resp.response[0].gets.capacity == 100.0

    asyncio.run(scenario())


@pytest.mark.parametrize("native", [False, True])
def test_batch_tick_priority_with_group_cap(native):
    async def scenario():
        clock = FakeClock()
        server = _make_server(clock, mode="batch", native=native)
        await _setup(server, clock)
        # Two priority resources in the shared 120-capacity group, plus a
        # plain proportional resource solved by the lane path.
        for client, res, wants, prio in [
            ("a", "prio-a", 100.0, 5),
            ("b", "prio-a", 50.0, 1),
            ("c", "prio-b", 100.0, 5),
            ("d", "plain", 40.0, 0),
        ]:
            await server.GetCapacity(_request(client, res, wants, prio), None)
        await server.tick_once()

        stores = {
            rid: dict(server.resources[rid].store.items())
            for rid in ("prio-a", "prio-b", "plain")
        }
        # Group usage capped at 120 < 200 total capacity.
        total_prio = sum(
            l.has for s in (stores["prio-a"], stores["prio-b"])
            for l in s.values()
        )
        assert total_prio == pytest.approx(120.0, rel=1e-6)
        # Within prio-a, the high-priority client is served first.
        assert stores["prio-a"]["a"].has > 0
        assert stores["prio-a"]["b"].has == pytest.approx(0.0, abs=1e-9)
        # Symmetric resources with symmetric demand split the group cap.
        assert stores["prio-a"]["a"].has == pytest.approx(
            stores["prio-b"]["c"].has, rel=1e-9
        )
        # The plain resource solves on the lane path, unaffected.
        assert stores["plain"]["d"].has == pytest.approx(40.0)
        # Priorities survive the write-back.
        assert stores["prio-a"]["a"].priority == 5
        assert stores["prio-a"]["b"].priority == 1

    asyncio.run(scenario())


def test_priority_survives_native_roundtrip():
    from doorman_tpu import native

    if not native.native_available():
        pytest.skip("native store build unavailable")
    clock = FakeClock()
    engine = native.StoreEngine(clock=clock)
    store = engine.store("res")
    store.assign("c", 60.0, 5.0, 1.0, 2.0, 1, priority=7)
    assert store.get("c").priority == 7
    assert dict(store.items())["c"].priority == 7
    *_, prio = engine.pack([store])
    assert list(prio) == [7]


def test_mixed_config_keeps_resident_path_for_lane_resources():
    """A config mixing PRIORITY_BANDS and lane resources must not lose
    the resident fast path: the lane subset ticks device-resident while
    the priority part goes through the BatchSolver — grants match the
    pure-batch world exactly on both."""
    async def scenario():
        clock = FakeClock()
        server = _make_server(clock, mode="batch", native=True)
        await _setup(server, clock)
        for client, res, wants, prio in [
            ("a", "prio-a", 100.0, 5),
            ("b", "prio-a", 50.0, 1),
            ("d", "plain", 140.0, 0),
            ("e", "plain", 80.0, 0),
        ]:
            await server.GetCapacity(_request(client, res, wants, prio), None)
        for _ in range(3):
            await server.tick_once()
            clock.t += 1.0

        # The resident path engaged for the lane subset...
        assert server._resident is not None and server._resident.ticks >= 1
        assert server._resident_ok
        # ...serving the lane resource through it (proportional: 100
        # capacity, wants 140+80 => scaled by 100/220, free-clamped)...
        plain = dict(server.resources["plain"].store.items())
        assert plain["d"].has + plain["e"].has == pytest.approx(100.0)
        assert plain["d"].has > plain["e"].has > 0
        # ...while the priority resource ticked through the batch part
        # (band 5 first: a gets min(100, group cap 120) within cap 100).
        prio_a = dict(server.resources["prio-a"].store.items())
        assert prio_a["a"].has == pytest.approx(100.0)
        assert prio_a["b"].has == pytest.approx(0.0, abs=1e-9)

    asyncio.run(scenario())
