"""Parity of the pallas banded water-fill against the XLA priority solve
(interpret mode on the CPU mesh)."""

import numpy as np
import jax
import jax.numpy as jnp

from doorman_tpu.solver.priority import (
    PriorityBatch,
    _alloc_banded,
    solve_priority,
)
from doorman_tpu.solver.pallas_priority import alloc_banded_pallas

jax.config.update("jax_enable_x64", True)


def _tables(seed, R=37, K=64, C=50, num_bands=4):
    rng = np.random.default_rng(seed)
    active = np.zeros((R, K), bool)
    for r in range(R):
        active[r, : rng.integers(1, C + 1)] = True
    return (
        jnp.asarray((rng.integers(0, 100, (R, K)) * active), jnp.float32),
        jnp.asarray((rng.integers(1, 4, (R, K)) * active), jnp.float32),
        jnp.asarray((rng.integers(0, num_bands, (R, K)) * active),
                    jnp.int32),
        jnp.asarray(active),
        jnp.asarray(rng.integers(20, 5000, R), jnp.float32),
    )


def test_alloc_banded_pallas_matches_xla():
    wants, weights, band, active, capacity = _tables(0)
    a = np.asarray(
        _alloc_banded(
            jnp.where(active, wants, 0.0), jnp.where(active, weights, 0.0),
            band, active, capacity, 4,
        )
    )
    b = np.asarray(
        alloc_banded_pallas(
            wants, weights, band, active, capacity, 4, interpret=True
        )
    )
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-4)


def test_solve_priority_pallas_interpret_matches():
    """Full solve (group bisection included) with the pallas alloc in
    interpret mode vs the plain XLA path."""
    rng = np.random.default_rng(1)
    R, K = 19, 64
    active = np.zeros((R, K), bool)
    for r in range(R):
        active[r, : rng.integers(1, 50)] = True
    batch = PriorityBatch(
        wants=jnp.asarray((rng.integers(0, 100, (R, K)) * active),
                          jnp.float32),
        weights=jnp.asarray((rng.integers(1, 4, (R, K)) * active),
                            jnp.float32),
        band=jnp.asarray((rng.integers(0, 4, (R, K)) * active), jnp.int32),
        active=jnp.asarray(active),
        capacity=jnp.asarray(rng.integers(50, 800, R), jnp.float32),
        group=jnp.asarray(rng.choice([-1, 0, 1], R), jnp.int32),
        group_cap=jnp.asarray([300.0, 500.0], jnp.float32),
    )
    plain = np.asarray(solve_priority(batch, num_bands=4))

    # Patch the kernel's pallas_call into interpret mode for the CPU run.
    import doorman_tpu.solver.pallas_priority as pp

    orig = pp.alloc_banded_pallas

    def interp(*args, **kwargs):
        kwargs["interpret"] = True
        return orig(*args, **kwargs)

    pp.alloc_banded_pallas = interp
    try:
        fused = np.asarray(
            solve_priority.__wrapped__(batch, num_bands=4, use_pallas=True)
        )
    finally:
        pp.alloc_banded_pallas = orig
    np.testing.assert_allclose(plain, fused, rtol=1e-5, atol=1e-3)
