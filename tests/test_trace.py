"""End-to-end tick tracing tests: span nesting, trace-context
propagation over loopback gRPC (the client's refresh span must be an
ancestor of the server's handler span), Chrome trace-event export
schema, per-phase histogram exposition, unclosed-span detection, the
/debug/traces + /debug index routes, the chaos virtual-time export, and
the tracer's overhead budget (disabled = no-op; enabled = microseconds).
"""

import asyncio
import json
import time
import urllib.request

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.chaos.trace_export import chrome_trace, write_chrome_trace
from doorman_tpu.client import Client
from doorman_tpu.obs import DebugServer, default_registry
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  safe_capacity: 5
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""


@pytest.fixture
def tracer():
    """The process-global tracer, enabled for the test and restored
    after (other tests must see it disabled and empty)."""
    tr = trace_mod.default_tracer()
    tr.clear()
    tr.enable()
    yield tr
    tr.disable()
    tr.clear()


def fetch(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read().decode()


# ----------------------------------------------------------------------
# Tracer core
# ----------------------------------------------------------------------


def test_span_nesting_and_instants(tracer):
    with tracer.span("outer", cat="t") as outer:
        tracer.instant("marker", cat="t")
        with tracer.span("inner", cat="t") as inner:
            pass
    events = {e.name: e for e in tracer.snapshot()}
    assert events["inner"].parent_id == outer.span_id
    assert events["inner"].trace_id == outer.trace_id
    assert events["marker"].parent_id == outer.span_id
    assert events["outer"].parent_id == 0
    assert events["outer"].dur >= events["inner"].dur >= 0.0


def test_disabled_tracer_records_nothing():
    tr = trace_mod.Tracer()
    assert not tr.enabled
    # One shared no-op context manager: no allocation per call.
    assert tr.span("a") is tr.span("b")
    with tr.span("a"):
        tr.instant("i")
        tr.add_complete("c", 0.0, 1.0)
    assert tr.snapshot() == []
    assert tr.open_spans() == []
    # Disabled tracer + no ambient span -> no metadata on the wire.
    assert trace_mod.grpc_metadata() == ()


def test_error_marks_span(tracer):
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("x")
    (ev,) = tracer.snapshot()
    assert ev.args["error"] == "ValueError"
    assert tracer.open_spans() == []


def test_unclosed_span_detection(tracer):
    cm = tracer.span("leaky")
    cm.__enter__()
    assert [s.name for s in tracer.open_spans()] == ["leaky"]
    cm.__exit__(None, None, None)
    assert tracer.open_spans() == []


def test_metadata_round_trip(tracer):
    with tracer.span("root"):
        md = trace_mod.grpc_metadata()
        assert md and md[0][0] == trace_mod.TRACE_METADATA_KEY
        ctx = trace_mod.parent_from_metadata(md)
        cur = trace_mod.current_context()
        assert ctx == cur
    # Garbage values parse to None, never raise.
    assert trace_mod.parent_from_metadata(
        ((trace_mod.TRACE_METADATA_KEY, "not-hex"),)
    ) is None
    assert trace_mod.parent_from_metadata(()) is None
    assert trace_mod.parent_from_grpc_context(None) is None


def test_ring_buffer_drops_oldest():
    tr = trace_mod.Tracer(capacity=4).enable()
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    names = [e.name for e in tr.snapshot()]
    assert names == ["s6", "s7", "s8", "s9"]


def test_jax_capture_noop_without_dir():
    with trace_mod.jax_capture(None):
        pass
    with trace_mod.jax_capture(""):
        pass


# ----------------------------------------------------------------------
# Chrome export schema
# ----------------------------------------------------------------------


def test_chrome_export_schema(tracer):
    with tracer.span("a", cat="x"):
        with tracer.span("b", cat="x"):
            pass
    tracer.instant("mark", cat="x")
    doc = tracer.chrome_trace()
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = doc["traceEvents"]
    body = [e for e in events if e["ph"] not in ("M",)]
    assert body, "no span events exported"
    for ev in events:
        assert {"name", "ph", "pid", "tid"} <= set(ev)
    for ev in body:
        assert ev["ts"] >= 0.0
        if ev["ph"] == "X":
            assert ev["dur"] >= 0.0
            assert "span_id" in ev["args"]
    # ts is monotonic non-decreasing in export order.
    ts = [e.get("ts", 0.0) for e in events]
    assert ts == sorted(ts)
    # The whole document is valid JSON (what Perfetto loads).
    json.loads(tracer.chrome_json())


# ----------------------------------------------------------------------
# Overhead budget (tier-1 keeps instrumentation honest)
# ----------------------------------------------------------------------


@pytest.mark.perf
def test_trace_overhead_budget():
    tr = trace_mod.Tracer()

    def cost(n=1000):
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("hot"):
                pass
        return (time.perf_counter() - t0) / n

    # Disabled: the shared no-op; generous 2 µs bound (it is one method
    # call and one `with`).
    disabled = min(cost() for _ in range(3))
    assert disabled < 2e-6, f"disabled span costs {disabled * 1e6:.2f} µs"

    tr.enable()
    # Enabled: ring-buffer append budget is ~10 µs; asserted loosely
    # (5x) so a noisy CI box cannot flake it while a 100 µs regression
    # still fails.
    enabled = min(cost() for _ in range(3))
    assert enabled < 50e-6, f"enabled span costs {enabled * 1e6:.2f} µs"


# ----------------------------------------------------------------------
# Loopback gRPC propagation + phase histograms + debug routes
# ----------------------------------------------------------------------


def test_loopback_trace_propagation_and_debug_pages(tracer):
    """The acceptance-criterion run: a real client refreshing against a
    real batch server over loopback gRPC, tracing enabled. The export
    must contain client refresh -> server GetCapacity parented across
    the hop, solver ticks with upload/solve/download/apply children
    (native store -> device-resident tick path; the python-store batch
    path's pack/solve/apply is covered by the same assertions when the
    native engine is unavailable), /metrics must expose per-phase
    histograms with non-zero counts, and no instrumented path may leak
    an open span."""
    from doorman_tpu import native

    native_store = native.native_available()
    component = "resident" if native_store else "batch"
    # The resident path runs the fused one-launch tick by default: the
    # device window is one "fused" phase span (round-trip mode would
    # emit upload/solve — see tests/test_fused_tick.py).
    phases = (
        ("fused", "download", "apply")
        if native_store
        else ("pack", "solve", "apply")
    )

    async def body():
        server = CapacityServer(
            "trace-server", TrivialElection(),
            minimum_refresh_interval=0.0, mode="batch",
            native_store=native_store,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        server.current_master = f"127.0.0.1:{port}"

        debug = DebugServer(host="127.0.0.1")
        debug.add_server(server, asyncio.get_running_loop())
        dport = debug.start()

        client = await Client.connect(
            f"127.0.0.1:{port}", "trace-client",
            minimum_refresh_interval=0.0,
        )
        res = await client.resource("r0", wants=40)
        cap = await asyncio.wait_for(res.capacity().get(), timeout=5)
        assert cap == 40.0
        # Two ticks: the resident path pipelines, so download/apply of
        # tick 1's grants land during tick 2's collect.
        await server.tick_once()
        await server.tick_once()

        loop = asyncio.get_running_loop()
        status, text = await loop.run_in_executor(
            None, fetch, dport, "/metrics"
        )
        status_traces, traces_page = await loop.run_in_executor(
            None, fetch, dport, "/debug/traces"
        )
        status_chrome, chrome = await loop.run_in_executor(
            None, fetch, dport, "/debug/traces?format=chrome"
        )
        status_index, index = await loop.run_in_executor(
            None, fetch, dport, "/debug"
        )

        await client.close()
        debug.stop()
        await server.stop()
        return (status, text, status_traces, traces_page,
                status_chrome, chrome, status_index, index)

    (status, text, status_traces, traces_page,
     status_chrome, chrome, status_index, index) = asyncio.run(body())

    # -- span parentage across the gRPC hop ---------------------------
    by_name = {}
    for ev in tracer.snapshot():
        by_name.setdefault(ev.name, []).append(ev)
    refresh = by_name["client.refresh"][0]
    rpc = by_name["client.GetCapacity"][0]
    handler = by_name["server.GetCapacity"][0]
    assert rpc.parent_id == refresh.span_id
    assert handler.parent_id == rpc.span_id
    assert handler.trace_id == refresh.trace_id

    # -- the solver tick spans have phase children --------------------
    tick_ids = {t.span_id for t in by_name["server.tick"]}
    for phase in phases:
        assert phase in by_name, phase
        ev = by_name[phase][0]
        assert ev.parent_id in tick_ids, phase
        assert ev.cat == f"phase:{component}"

    # -- no instrumented path leaks an open span ----------------------
    assert tracer.open_spans() == []

    # -- /metrics: per-phase histograms with non-zero counts ----------
    assert status == 200
    for phase in phases:
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith(
                "doorman_tick_phase_seconds_count"
                f'{{component="{component}",phase="{phase}"}}'
            )
        )
        assert int(line.rsplit(" ", 1)[1]) > 0, line
    assert (
        f'doorman_tick_phase_last_seconds{{component="{component}"'
        in text
    )

    # -- /debug/traces + index + chrome download ----------------------
    assert status_traces == 200
    assert "tracer enabled" in traces_page
    assert "server.GetCapacity" in traces_page
    assert status_index == 200
    assert "/debug/traces" in index and "/metrics" in index
    assert status_chrome == 200
    doc = json.loads(chrome)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"client.refresh", "server.GetCapacity", "server.tick",
            "fused" if native_store else "solve"} <= names


def test_direct_handler_call_tolerates_no_context(tracer):
    """Tests and tooling drive handlers with context=None; the tracing
    wrapper must not assume gRPC invocation metadata exists."""
    from doorman_tpu.proto import doorman_pb2 as pb

    async def body():
        server = CapacityServer(
            "nc-server", TrivialElection(), minimum_refresh_interval=0.0
        )
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        req = pb.GetCapacityRequest(client_id="c1")
        r = req.resource.add()
        r.resource_id = "r0"
        r.wants = 10.0
        out = await server.GetCapacity(req, None)
        assert out.response[0].gets.capacity == 10.0
        await server.stop()

    asyncio.run(body())
    assert [e.name for e in tracer.snapshot()
            if e.name == "server.GetCapacity"]
    assert tracer.open_spans() == []


def test_resident_phase_spans_and_histograms(tracer):
    """The device-resident tick path emits its phase laps (the fused
    device window by default, upload/solve in round-trip mode) as
    spans nested under the ambient tick span, and as per-phase
    histograms in the default registry. Both modes step so both
    vocabularies land."""
    from doorman_tpu import native

    if not native.native_available():
        pytest.skip("native engine unavailable")
    import numpy as np

    from doorman_tpu.core.resource import Resource
    from doorman_tpu.proto import doorman_pb2 as pb
    from doorman_tpu.solver.resident import ResidentDenseSolver

    engine = native.StoreEngine()
    tpl = pb.ResourceTemplate(
        identifier_glob="r0", capacity=100.0,
        algorithm=pb.Algorithm(
            kind=pb.Algorithm.PROPORTIONAL_SHARE,
            lease_length=60, refresh_interval=5,
        ),
    )
    res = Resource("r0", tpl, store_factory=engine.store)
    for c in range(4):
        res.store.assign(f"c{c}", 60.0, 5.0, 0.0, 10.0 * (c + 1), 1)
    solver = ResidentDenseSolver(
        engine, dtype=np.float64, rotate_ticks=1
    )
    with tracer.span("server.tick", cat="tick") as tick:
        solver.step([res])  # fused (default): one "fused" device lap
    solver.fused_tick = False
    res.store.assign("c0", 60.0, 5.0, 0.0, 15.0, 1)
    with tracer.span("server.tick", cat="tick") as tick2:
        solver.step([res])  # round-trip: upload + solve laps
    by_name = {}
    for ev in tracer.snapshot():
        by_name.setdefault(ev.name, []).append(ev)
    for phase, parent in (
        ("sweep", tick), ("drain", tick), ("pack", tick),
        ("fused", tick), ("download", tick), ("apply", tick),
        ("rebuild", tick), ("upload", tick2), ("solve", tick2),
    ):
        assert phase in by_name, phase
        ev = by_name[phase][0]
        assert ev.parent_id == parent.span_id, phase
        assert ev.cat == "phase:resident"
    assert tracer.open_spans() == []
    text = default_registry().expose()
    for phase in ("fused", "upload"):
        assert (
            'doorman_tick_phase_seconds_count{component="resident",'
            f'phase="{phase}"}}' in text
        )


# ----------------------------------------------------------------------
# Chaos: virtual-time Chrome export + fault/violation counters
# ----------------------------------------------------------------------


def test_chaos_chrome_export(tmp_path):
    verdict = {
        "plan": "unit",
        "tick_interval": 0.5,
        "event_log": [
            [2, "fault", "grpc_drop", "link:s0", 4],
            [3, "master", ["s1"]],
            [5, "violation", "capacity", "r0", "over by 1"],
            [6, "degraded"],
            [9, "converged", 3],
        ],
    }
    doc = chrome_trace(verdict)
    events = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    assert len(events) == 5
    fault = next(e for e in events if e["ph"] == "X")
    assert fault["name"] == "grpc_drop(link:s0)"
    assert fault["ts"] == 2 * 0.5 * 1e6
    assert fault["dur"] == 4 * 0.5 * 1e6
    for ev in events:
        assert {"name", "ph", "pid", "tid", "ts"} <= set(ev)
    ts = [e.get("ts", 0.0) for e in doc["traceEvents"]]
    assert ts == sorted(ts)
    out = tmp_path / "chaos_trace.json"
    write_chrome_trace(verdict, str(out))
    json.loads(out.read_text())


def test_chaos_counters_in_default_registry():
    from doorman_tpu.chaos.plan import FaultEvent, FaultPlan
    from doorman_tpu.chaos.runner import ChaosRunner

    plan = FaultPlan(name="unit-counters", seed=0, setup={})
    runner = ChaosRunner(plan)
    before = runner._faults_counter.value("grpc_drop")
    runner._apply_event(
        FaultEvent(kind="grpc_drop", target="link:s0", at_tick=5,
                   duration_ticks=2),
        tick=5,
    )
    assert runner._faults_counter.value("grpc_drop") == before + 1
    from doorman_tpu.chaos.invariants import Violation

    vbefore = runner._violations_counter.value("capacity")
    runner._record_violation(Violation(1, "capacity", "r0", "x"))
    assert runner._violations_counter.value("capacity") == vbefore + 1
    text = default_registry().expose()
    assert "doorman_chaos_faults_injected" in text
    assert "doorman_chaos_invariant_violations" in text
