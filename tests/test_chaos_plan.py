"""FaultPlan serialization and replay determinism.

The plan is the replay artifact: its JSON round-trips byte-identically,
and running the same plan twice produces the same event log (the
acceptance contract for every chaos scenario)."""

import asyncio

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.chaos import ChaosRunner, FaultEvent, FaultPlan, get_plan
from doorman_tpu.chaos.plans import PLANS


def test_plan_json_round_trip_is_byte_identical():
    for name in PLANS:
        plan = get_plan(name)
        text = plan.to_json()
        again = FaultPlan.from_json(text)
        assert again == plan
        assert again.to_json() == text  # canonical form is a fixpoint


def test_plan_save_load(tmp_path):
    plan = get_plan("master_flap")
    path = tmp_path / "plan.json"
    plan.save(str(path))
    assert FaultPlan.load(str(path)) == plan


def test_unknown_fault_kind_rejected():
    with pytest.raises(ValueError):
        FaultEvent(at_tick=0, kind="gremlins")


def test_event_inside_warmup_rejected():
    with pytest.raises(ValueError):
        FaultPlan(
            name="bad", seed=0, setup={},
            events=[FaultEvent(at_tick=1, kind="kv_drop")],
            warmup_ticks=5,
        )


def test_same_seed_and_plan_replays_identical_event_log():
    plan = get_plan("master_flap")
    v1 = asyncio.run(ChaosRunner(plan).run())
    v2 = asyncio.run(ChaosRunner(FaultPlan.from_json(plan.to_json())).run())
    assert v1["event_log"] == v2["event_log"]
    assert v1["log_sha256"] == v2["log_sha256"]
    assert v1["ok"] and v2["ok"]
