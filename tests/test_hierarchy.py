"""Multi-node tests without a real cluster (capability parity with
reference server_test.go:555-658): a root server and an intermediate server
on loopback; the intermediate aggregates its clients' demand upstream and
re-templates itself from the root's grants, converging from grant 0 to full
capacity within a few refresh cycles."""

import asyncio

import pytest

import tests.conftest  # noqa: F401
import grpc

from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import CapacityStub
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer, _band_key

ROOT_CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""

BANDED_ROOT_CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PRIORITY_BANDS, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""


def run(coro):
    return asyncio.run(coro)


def capacity_request(client_id, resource_id, wants, priority=0):
    req = pb.GetCapacityRequest(client_id=client_id)
    rr = req.resource.add()
    rr.resource_id = resource_id
    rr.wants = wants
    rr.priority = priority
    return req


async def make_root():
    root = CapacityServer(
        "root", TrivialElection(), minimum_refresh_interval=0.0
    )
    port = await root.start(0, host="127.0.0.1")
    await root.load_config(parse_yaml_config(ROOT_CONFIG))
    await asyncio.sleep(0)
    root.current_master = f"127.0.0.1:{port}"
    return root, f"127.0.0.1:{port}"


async def make_intermediate(root_addr, server_id="intermediate"):
    mid = CapacityServer(
        server_id,
        TrivialElection(),
        parent_addr=root_addr,
        minimum_refresh_interval=0.1,
    )
    port = await mid.start(0, host="127.0.0.1")
    await asyncio.sleep(0)
    mid.current_master = f"127.0.0.1:{port}"
    return mid, f"127.0.0.1:{port}"


def test_intermediate_converges_to_root_capacity():
    async def body():
        root, root_addr = await make_root()
        mid, mid_addr = await make_intermediate(root_addr)
        try:
            # The intermediate starts with the default "*" template
            # (capacity 0) and a 20s learning mode; disable learning so the
            # convergence is driven by the parent refresh alone.
            mid.became_master_at -= 1000

            async with grpc.aio.insecure_channel(mid_addr) as ch:
                stub = CapacityStub(ch)
                out = await stub.GetCapacity(
                    capacity_request("client-a", "res0", 40.0)
                )
                first = out.response[0].gets.capacity

                # Learning-mode resource on a fresh intermediate replays
                # has=0; after updater cycles the parent grants flow down.
                granted = first
                for _ in range(60):
                    await asyncio.sleep(0.1)
                    res = mid.resources.get("res0")
                    if res is not None:
                        res.learning_mode_end = 0.0
                    out = await stub.GetCapacity(
                        capacity_request("client-a", "res0", 40.0)
                    )
                    granted = out.response[0].gets.capacity
                    if granted == 40.0:
                        break
                assert granted == 40.0, f"never converged, last={granted}"

            # The root now tracks the intermediate's aggregated demand,
            # one sub-lease per priority band (client-a sent priority 0).
            root_res = root.resources.get("res0")
            assert root_res is not None
            band = _band_key("intermediate", 0)
            assert root_res.store.has_client(band)
            assert root_res.store.get(band).wants == 40.0
        finally:
            await mid.stop()
            await root.stop()

    run(body())


def test_parent_grant_becomes_intermediate_capacity():
    async def body():
        root, root_addr = await make_root()
        mid, mid_addr = await make_intermediate(root_addr)
        try:
            mid.became_master_at -= 1000
            async with grpc.aio.insecure_channel(mid_addr) as ch:
                stub = CapacityStub(ch)
                # Two clients on the intermediate; total wants 150 exceeds
                # the root's capacity 100, so the intermediate's lease (and
                # therefore its local resource capacity) caps at 100.
                for _ in range(60):
                    await asyncio.sleep(0.1)
                    res = mid.resources.get("shared")
                    if res is not None:
                        res.learning_mode_end = 0.0
                    await stub.GetCapacity(
                        capacity_request("c1", "shared", 90.0)
                    )
                    await stub.GetCapacity(
                        capacity_request("c2", "shared", 60.0)
                    )
                    res = mid.resources.get("shared")
                    if res is not None and 0 < res.capacity <= 100.0:
                        break
                res = mid.resources.get("shared")
                assert res is not None
                assert 0 < res.capacity <= 100.0
                # Grants to local clients never exceed the parent lease.
                assert res.store.sum_has <= res.capacity + 1e-9
        finally:
            await mid.stop()
            await root.stop()

    run(body())


def test_priority_bands_flow_through_two_hops():
    """Two intermediates with different band mixes against a
    PRIORITY_BANDS root (capacity 100, total demand 180): the high band
    is served in full and the leftovers split evenly across the two
    priority-1 bands, through the client->intermediate->root hops
    (reference multi-band aggregation:
    simulation/server_state_wrapper.py:305-334)."""

    async def body():
        root, root_addr = await make_root()
        await root.load_config(parse_yaml_config(BANDED_ROOT_CONFIG))
        mid1, mid1_addr = await make_intermediate(root_addr, "mid1")
        mid2, mid2_addr = await make_intermediate(root_addr, "mid2")
        try:
            mid1.became_master_at -= 1000
            mid2.became_master_at -= 1000
            async with grpc.aio.insecure_channel(mid1_addr) as ch1, \
                    grpc.aio.insecure_channel(mid2_addr) as ch2:
                stub1, stub2 = CapacityStub(ch1), CapacityStub(ch2)
                grants = {}
                for _ in range(100):
                    await asyncio.sleep(0.1)
                    for mid in (mid1, mid2):
                        res = mid.resources.get("shared")
                        if res is not None:
                            res.learning_mode_end = 0.0
                    o_hi = await stub1.GetCapacity(
                        capacity_request("hi", "shared", 60.0, priority=2)
                    )
                    o_lo = await stub1.GetCapacity(
                        capacity_request("lo", "shared", 60.0, priority=1)
                    )
                    o_lo2 = await stub2.GetCapacity(
                        capacity_request("lo2", "shared", 60.0, priority=1)
                    )
                    grants = {
                        "hi": o_hi.response[0].gets.capacity,
                        "lo": o_lo.response[0].gets.capacity,
                        "lo2": o_lo2.response[0].gets.capacity,
                    }
                    if (
                        abs(grants["hi"] - 60.0) < 1e-6
                        and abs(grants["lo"] - 20.0) < 1e-6
                        and abs(grants["lo2"] - 20.0) < 1e-6
                    ):
                        break
                assert abs(grants["hi"] - 60.0) < 1e-6, grants
                assert abs(grants["lo"] - 20.0) < 1e-6, grants
                assert abs(grants["lo2"] - 20.0) < 1e-6, grants

            # The root sees each intermediate's bands separately, at the
            # band-correct granted amounts.
            root_res = root.resources.get("shared")
            assert root_res is not None
            hi_band = root_res.store.get(_band_key("mid1", 2))
            lo_band1 = root_res.store.get(_band_key("mid1", 1))
            lo_band2 = root_res.store.get(_band_key("mid2", 1))
            assert hi_band.wants == 60.0
            assert lo_band1.wants == 60.0
            assert lo_band2.wants == 60.0
            assert abs(hi_band.has - 60.0) < 1e-6
            assert abs(lo_band1.has - 20.0) < 1e-6
            assert abs(lo_band2.has - 20.0) < 1e-6
        finally:
            await mid1.stop()
            await mid2.stop()
            await root.stop()

    run(body())
