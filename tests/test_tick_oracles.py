"""Tests for the per-tick (batch snapshot) numpy oracles that define the
solver semantics, and their relationship to the reference's incremental
algorithms."""

import numpy as np
import pytest

from doorman_tpu.algorithms import tick


class TestProportionalSnapshot:
    def test_underload_grants_wants(self):
        wants = np.array([10.0, 20.0, 30.0])
        has = np.zeros(3)
        gets = tick.proportional_snapshot(100.0, wants, has)
        np.testing.assert_array_equal(gets, wants)

    def test_overload_scales_proportionally(self):
        # Matches simulation/algo_proportional.py: proportion = cap/all_wants.
        wants = np.array([60.0, 60.0, 80.0])
        has = np.zeros(3)
        gets = tick.proportional_snapshot(100.0, wants, has)
        np.testing.assert_allclose(gets, wants * (100.0 / 200.0))
        assert gets.sum() <= 100.0 + 1e-12

    def test_free_capacity_clamps(self):
        # Other clients hold the whole capacity from the previous tick; a
        # newcomer is clamped by the free capacity (0 here).
        wants = np.array([50.0, 50.0, 50.0])
        has = np.array([50.0, 50.0, 0.0])
        gets = tick.proportional_snapshot(100.0, wants, has)
        assert gets[2] == 0.0

    def test_self_has_excluded_from_leases(self):
        # A single client holding everything can still be re-granted: its own
        # previous lease does not count against its free capacity.
        wants = np.array([80.0])
        has = np.array([100.0])
        gets = tick.proportional_snapshot(100.0, wants, has)
        assert gets[0] == 80.0


class TestProportionalSequential:
    def test_matches_snapshot_on_steady_state(self):
        # At a fixed point (has == the snapshot solution, all free) the
        # sequential replay returns the same grants.
        rng = np.random.default_rng(0)
        wants = rng.integers(1, 100, 50).astype(np.float64)
        has = tick.proportional_snapshot(800.0, wants, np.zeros(50))
        seq = tick.proportional_sequential(800.0, wants, has)
        snap = tick.proportional_snapshot(800.0, wants, has)
        np.testing.assert_allclose(seq, snap)

    def test_order_dependence_matches_reference_story(self):
        # Fresh store, overload: early clients squeeze the late one, exactly
        # like the unpreloaded reference table.
        wants = np.array([60.0, 75.0, 10.0])
        has = np.zeros(3)
        gets = tick.proportional_sequential(145.0, wants, has)
        # all_wants = 145 >= cap: everyone scaled by 145/145 = 1, then
        # clamped by evolving free capacity.
        assert gets[0] == 60.0
        assert gets[1] == 75.0
        assert gets[2] == 10.0

    def test_never_overcommits(self):
        rng = np.random.default_rng(1)
        for _ in range(20):
            n = rng.integers(1, 30)
            wants = rng.integers(0, 100, n).astype(np.float64)
            has = rng.integers(0, 50, n).astype(np.float64)
            cap = float(rng.integers(1, 200))
            gets = tick.proportional_sequential(cap, wants, has)
            assert np.sum(gets) <= cap + 1e-9


class TestProportionalTopup:
    def test_matches_go_table_preloaded(self):
        # Reference algorithm_test.go TestProportionalShare, preloaded store:
        # equal share 40, extra capacity 30 from c2, extra need 40.
        wants = np.array([60.0, 60.0, 10.0])
        has = np.zeros(3)
        sub = np.ones(3)
        gets = tick.proportional_topup_snapshot(120.0, wants, has, sub)
        np.testing.assert_allclose(gets, [55.0, 55.0, 10.0])

    def test_matches_go_table_subclients(self):
        wants = np.array([65.0, 45.0, 20.0])
        has = np.zeros(3)
        sub = np.array([3.0, 2.0, 1.0])
        gets = tick.proportional_topup_snapshot(120.0, wants, has, sub)
        np.testing.assert_allclose(gets, [60.0, 40.0, 20.0])

    def test_underload(self):
        wants = np.array([5.0, 10.0])
        gets = tick.proportional_topup_snapshot(
            100.0, wants, np.zeros(2), np.ones(2)
        )
        np.testing.assert_array_equal(gets, wants)


class TestFairShareWaterfill:
    # The same tables as the reference's FairShare tests: full water-filling
    # agrees with the two-round approximation on all of them.
    @pytest.mark.parametrize(
        "wants,sub,cap,expected",
        [
            ([1000, 60, 10], [1, 1, 1], 120, [55, 55, 10]),
            ([1000, 50, 10], [1, 1, 1], 120, [60, 50, 10]),
            ([1000, 500, 200], [6, 4, 2], 120, [60, 40, 20]),
            ([2000, 500, 700], [10, 10, 30], 1000, [200, 200, 600]),
        ],
    )
    def test_reference_tables(self, wants, sub, cap, expected):
        gets = tick.fair_share_waterfill(
            float(cap), np.array(wants, dtype=np.float64), np.array(sub, dtype=np.float64)
        )
        np.testing.assert_allclose(gets, expected)

    def test_sums_to_capacity_in_overload(self):
        rng = np.random.default_rng(2)
        for _ in range(50):
            n = int(rng.integers(1, 40))
            wants = rng.integers(0, 1000, n).astype(np.float64)
            sub = rng.integers(1, 10, n).astype(np.float64)
            cap = float(rng.integers(1, 500))
            gets = tick.fair_share_waterfill(cap, wants, sub)
            if wants.sum() <= cap:
                np.testing.assert_array_equal(gets, wants)
            else:
                assert abs(gets.sum() - cap) < 1e-6
            # max-min property: nobody below their saturated fair level
            # unless fully satisfied.
            assert np.all(gets <= wants + 1e-12)

    def test_equal_share_floor(self):
        # In overload, a client wanting at least its equal share never gets
        # less than the water level * weight >= equal share of capacity.
        wants = np.array([100.0, 100.0, 100.0, 1.0])
        sub = np.ones(4)
        cap = 40.0
        gets = tick.fair_share_waterfill(cap, wants, sub)
        level = tick.waterfill_level(cap, wants, sub)
        assert level >= cap / 4 - 1e-12
        np.testing.assert_allclose(gets[:3], level)
        assert gets[3] == 1.0


class TestPointwise:
    def test_none_static_learn(self):
        wants = np.array([5.0, 500.0])
        has = np.array([1.0, 2.0])
        np.testing.assert_array_equal(tick.none_tick(wants), wants)
        np.testing.assert_array_equal(
            tick.static_tick(100.0, wants), [5.0, 100.0]
        )
        np.testing.assert_array_equal(tick.learn_tick(has), has)
