"""dm_decide (the one-call C request path) vs the scalar Python oracle.

The C decide replicates algorithms/scalar.py expression-for-expression,
so on IDENTICAL store states its grants must be BIT-identical — the
comparison runs two native engines through the same request stream, one
deciding in C (Resource.decide fast path), one through the Python
algorithm closures, and asserts exact equality per request and over the
final stores. (Native-vs-Python-STORE comparisons cannot be bit-exact:
the two stores accumulate their running sums in different removal
orders.)"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.algorithms import scalar
from doorman_tpu.core.resource import Resource
from doorman_tpu.proto import doorman_pb2 as pb

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

CASES = [
    (pb.Algorithm.NO_ALGORITHM, None),
    (pb.Algorithm.STATIC, None),
    (pb.Algorithm.PROPORTIONAL_SHARE, None),
    (pb.Algorithm.PROPORTIONAL_SHARE, "topup"),
    (pb.Algorithm.FAIR_SHARE, None),
]


def make_template(kind, variant):
    algo = pb.Algorithm(kind=kind, lease_length=60, refresh_interval=5)
    if variant:
        p = algo.parameters.add()
        p.name = "variant"
        p.value = variant
    return pb.ResourceTemplate(
        identifier_glob="r", capacity=500.0, algorithm=algo
    )


@pytest.mark.parametrize("kind,variant", CASES)
def test_c_decide_bit_identical_to_scalar_oracle(kind, variant):
    rng = np.random.default_rng(int(kind) * 7 + 1)
    t = [1000.0]
    clock = lambda: t[0]
    tpl = make_template(kind, variant)
    eng_a = native.StoreEngine(clock=clock)
    eng_b = native.StoreEngine(clock=clock)
    ra = Resource("r", tpl, clock=clock, store_factory=eng_a.store)
    rb = Resource("r", tpl, clock=clock, store_factory=eng_b.store)
    assert ra._decide_fast is not None  # the C path is actually on
    pyalgo = scalar.get_algorithm(tpl.algorithm)
    grants_a, grants_b = {}, {}
    for i in range(2500):
        c = f"c{rng.integers(0, 40)}"
        wants = float(rng.integers(1, 200))
        sub = int(rng.integers(1, 4))
        la = ra.decide(scalar.Request(c, grants_a.get(c, 0.0), wants, sub))
        rb.store.clean()
        lb = pyalgo(
            rb.store, rb.capacity,
            scalar.Request(c, grants_b.get(c, 0.0), wants, sub),
        )
        assert la.has == lb.has, (i, c, la.has, lb.has)
        assert la.expiry == lb.expiry and la.wants == lb.wants
        grants_a[c], grants_b[c] = la.has, lb.has
        if rng.random() < 0.05:
            ra.store.release(c)
            rb.store.release(c)
            grants_a.pop(c, None)
            grants_b.pop(c, None)
        if rng.random() < 0.02:
            t[0] += float(rng.integers(1, 80))  # expiry sweeps
    a = dict(ra.store.items())
    b = dict(rb.store.items())
    assert a.keys() == b.keys()
    for k in a:
        assert a[k].has == b[k].has and a[k].wants == b[k].wants


def test_learning_mode_routes_to_c_learn():
    t = [1000.0]
    clock = lambda: t[0]
    tpl = make_template(pb.Algorithm.PROPORTIONAL_SHARE, None)
    eng = native.StoreEngine(clock=clock)
    res = Resource(
        "r", tpl, clock=clock, learning_mode_end=t[0] + 100,
        store_factory=eng.store,
    )
    lease = res.decide(scalar.Request("x", 42.0, 50.0, 1))
    assert lease.has == 42.0  # learning replays the reported grant
    t[0] += 200  # learning window over: real algorithm resumes
    lease = res.decide(scalar.Request("x", lease.has, 50.0, 1))
    assert lease.has == 50.0  # only client, fits capacity


def test_priority_bands_stays_on_python_path():
    """AlgoKind.PRIORITY_BANDS (5) must never reach dm_decide (whose
    LEARN code is 6 precisely to avoid the collision)."""
    t = [1000.0]
    clock = lambda: t[0]
    algo = pb.Algorithm(
        kind=pb.Algorithm.PRIORITY_BANDS, lease_length=60,
        refresh_interval=5,
    )
    tpl = pb.ResourceTemplate(
        identifier_glob="r", capacity=100.0, algorithm=algo
    )
    eng = native.StoreEngine(clock=clock)
    res = Resource("r", tpl, clock=clock, store_factory=eng.store)
    lease = res.decide(scalar.Request("a", 0.0, 80.0, 1, priority=3))
    assert lease.has == 80.0
    # The banded scalar path (not C) decided: a higher-priority claim
    # displaces on the next round, the C lanes have no such behavior.
    lease_b = res.decide(scalar.Request("b", 0.0, 100.0, 1, priority=9))
    assert lease_b.has == 20.0


def test_expiry_sweep_inside_c_decide():
    t = [1000.0]
    clock = lambda: t[0]
    tpl = make_template(pb.Algorithm.PROPORTIONAL_SHARE, None)
    eng = native.StoreEngine(clock=clock)
    res = Resource("r", tpl, clock=clock, store_factory=eng.store)
    res.decide(scalar.Request("dead", 0.0, 400.0, 1))
    t[0] += 120  # past the 60s lease
    lease = res.decide(scalar.Request("live", 0.0, 400.0, 1))
    # The dead lease was swept inside the same C call, so the whole
    # capacity is free for the new client.
    assert lease.has == 400.0
    assert not res.store.has_client("dead")


def test_refresh_grant_preserves_has_and_marks_demand():
    """The batch-mode one-call path: has preserved (the tick is the
    only writer of grants), demand recorded, expiry stamped; unknown
    clients return None; wants-only churn marks the slot wants-dirty
    while a subclient change marks it full."""
    t = [1000.0]
    clock = lambda: t[0]
    eng = native.StoreEngine(clock=clock)
    st = eng.store("r")
    st.assign("c", 60.0, 5.0, 7.5, 10.0, 1)
    eng.chunk_config(np.array([st._rid], np.int32), 8)

    lease = st.refresh_grant("c", 60.0, 5.0, 42.0, 1, 0)
    assert lease is not None
    assert lease.has == 7.5 and lease.wants == 42.0
    assert lease.expiry == t[0] + 60.0
    got = st.get("c")
    assert got.has == 7.5 and got.wants == 42.0
    slots, lvl = eng.drain_slots(st._rid)
    assert list(slots) == [0] and list(lvl) == [1]  # wants-only

    # Subclient change -> full-dirty slot.
    st.refresh_grant("c", 60.0, 5.0, 42.0, 3, 0)
    slots, lvl = eng.drain_slots(st._rid)
    assert list(slots) == [0] and list(lvl) == [2]
    assert st.count == 3

    assert st.refresh_grant("nobody", 60.0, 5.0, 1.0, 1, 0) is None
