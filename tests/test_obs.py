"""Observability tests: metrics registry exposition, server/client
instrumentation, and the debug HTTP pages (capability parity with
reference status_test.go:42-70 — pages served over real HTTP)."""

import asyncio
import urllib.request

import tests.conftest  # noqa: F401

from doorman_tpu.client import Client
from doorman_tpu.obs import (
    DebugServer,
    Registry,
    add_status_part,
    instrument_server,
)
from doorman_tpu.obs.metrics import instrument_client
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  safe_capacity: 5
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""


def fetch(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read().decode()


def test_counter_gauge_exposition():
    reg = Registry()
    c = reg.counter("requests_total", "Total requests.", labels=("method",))
    c.inc("GetCapacity")
    c.inc("GetCapacity")
    c.inc("Release", by=3)
    g = reg.gauge("temperature", "Now.")
    g.set(36.5)
    text = reg.expose()
    assert "# TYPE requests_total counter" in text
    assert 'requests_total{method="GetCapacity"} 2' in text
    assert 'requests_total{method="Release"} 3' in text
    assert "# HELP temperature Now." in text
    assert "temperature 36.5" in text


def test_request_log_stamps_via_injected_clock():
    # The clock is an injectable seam (doormanlint seeded-determinism):
    # a chaos-driven server's samples must carry VIRTUAL time, and the
    # explicit `when` override must win over the clock.
    from doorman_tpu.obs.requests import RequestLog

    t = [1000.0]
    log = RequestLog(clock=lambda: t[0])
    log.record("GetCapacity", "c1", ["r0"], 5.0, 0.01, False)
    t[0] = 2000.0
    log.record("Release", "c1", ["r0"], 0.0, 0.01, False, when=42.0)
    newest, oldest = log.snapshot()
    assert oldest.when == 1000.0
    assert newest.when == 42.0


def test_histogram_exposition():
    reg = Registry()
    h = reg.histogram("latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.expose()
    assert 'latency_bucket{le="0.1"} 1' in text
    assert 'latency_bucket{le="1"} 2' in text
    assert 'latency_bucket{le="+Inf"} 3' in text
    assert "latency_count 3" in text
    assert abs(h.sum() - 5.55) < 1e-9


def test_registry_dedupes_by_name():
    reg = Registry()
    a = reg.counter("x")
    b = reg.counter("x")
    assert a is b


def test_label_escaping():
    reg = Registry()
    c = reg.counter("c", labels=("v",))
    c.inc('say "hi"\n')
    assert 'c{v="say \\"hi\\"\\n"} 1' in reg.expose()


def test_instrumented_server_and_debug_pages():
    async def body():
        server = CapacityServer(
            "obs-server", TrivialElection(), minimum_refresh_interval=0.0
        )
        reg = Registry()
        instrument_server(server, reg)
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        server.current_master = f"127.0.0.1:{port}"

        debug = DebugServer(host="127.0.0.1", registry=reg)
        debug.add_server(server, asyncio.get_running_loop())
        dport = debug.start()
        add_status_part("test-part", lambda: "<p>part-content-xyz</p>")

        client = await Client.connect(
            f"127.0.0.1:{port}", "client-1", minimum_refresh_interval=0.0
        )
        instrument_client(client, reg)
        res = await client.resource("r0", wants=40)
        cap = await asyncio.wait_for(res.capacity().get(), timeout=5)
        assert cap == 40.0

        loop = asyncio.get_running_loop()
        status, text = await loop.run_in_executor(
            None, fetch, dport, "/metrics"
        )
        assert status == 200
        assert (
            'doorman_server_requests_count{method="GetCapacity"} 1' in text
        )
        assert "doorman_server_requests_durations_bucket" in text
        assert 'doorman_server_resource_wants{resource="r0"} 40' in text
        assert "doorman_server_is_master 1" in text
        assert "doorman_client_requests_durations_count" in text

        status, page = await loop.run_in_executor(
            None, fetch, dport, "/debug/status"
        )
        assert status == 200
        assert "obs-server" in page
        assert "r0" in page
        assert "part-content-xyz" in page

        status, page = await loop.run_in_executor(
            None, fetch, dport, "/debug/resources?resource=r0"
        )
        assert status == 200
        assert "client-1" in page

        # The request sample ring renders the RPC we just made.
        status, page = await loop.run_in_executor(
            None, fetch, dport, "/debug/requests"
        )
        assert status == 200
        assert "GetCapacity" in page
        assert "client-1" in page
        assert "r0" in page
        sample = server.request_log.snapshot(1)[0]
        assert sample.method == "GetCapacity"
        assert sample.wants == 40.0
        assert not sample.error

        status, _ = await loop.run_in_executor(None, fetch, dport, "/healthz")
        assert status == 200

        await client.close()
        debug.stop()
        await server.stop()

    asyncio.run(body())


def test_debug_frontend_page_renders_inline_pool():
    """/debug/frontend renders the serving-plane pool's liveness, held
    streams, and per-worker pump counters; ?format=json mirrors the
    pool's status dict. Servers without a pool say so instead of 500."""
    import json

    from doorman_tpu.proto import doorman_stream_pb2 as spb

    async def body():
        server = CapacityServer(
            "fe-obs", TrivialElection(), minimum_refresh_interval=0.0,
            mode="immediate", stream_push=True, stream_shards=4,
        )
        pool = server.attach_frontend(2)
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        server.current_master = f"127.0.0.1:{port}"

        bare = CapacityServer(
            "fe-none", TrivialElection(), minimum_refresh_interval=0.0
        )

        req = spb.WatchCapacityRequest(client_id="w1")
        rr = req.resource.add()
        rr.resource_id = "r0"
        rr.wants = 5.0
        sub = server._streams.subscribe(req)
        server._stream_match_add(sub)
        pool.pump_all()

        debug = DebugServer(host="127.0.0.1", registry=Registry())
        loop = asyncio.get_running_loop()
        debug.add_server(server, loop)
        debug.add_server(bare, loop)
        dport = debug.start()
        try:
            status, page = await loop.run_in_executor(
                None, fetch, dport, "/debug/frontend"
            )
            assert status == 200
            assert "mode: inline" in page
            assert "workers live: 2/2" in page
            assert "held: 1" in page
            assert "no frontend pool attached" in page  # fe-none
            status, text = await loop.run_in_executor(
                None, fetch, dport, "/debug/frontend?format=json"
            )
            assert status == 200
            st = json.loads(text)
            assert st["fe-none"] is None
            assert st["fe-obs"]["held"] == 1
            assert st["fe-obs"]["live"] == [0, 1]
            assert sum(
                w["frames"] for w in st["fe-obs"]["per_worker"]
            ) >= 1
        finally:
            debug.stop()
            await server.stop()

    asyncio.run(body())


def test_batch_tick_profiler_trace(tmp_path):
    """--profile-dir writes a JAX profiler trace of the first ticks."""
    import jax

    jax.config.update("jax_enable_x64", True)

    async def body():
        server = CapacityServer(
            "prof-server", TrivialElection(), minimum_refresh_interval=0.0,
            mode="batch", profile_dir=str(tmp_path), profile_ticks=1,
        )
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        from doorman_tpu.proto import doorman_pb2 as pb

        req = pb.GetCapacityRequest()
        req.client_id = "c1"
        r = req.resource.add()
        r.resource_id = "r0"
        r.wants = 10.0
        await server.GetCapacity(req, None)
        await server.tick_once()
        await server.tick_once()
        assert not server._profiling

    asyncio.run(body())
    traces = list(tmp_path.rglob("*"))
    assert any(p.is_file() for p in traces), "no profiler trace written"
