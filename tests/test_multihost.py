"""Multi-host packing math and the host-shard solve path on the CPU mesh.

Everything pure is pinned here: the per-host block padding, the
deal/reassemble identity, the process-ordered mesh layout, and the
single-process `pack_process_edges` path solved end-to-end against the
single-device oracle (the same path `__graft_entry__.dryrun_multichip`
exercises). The final test then runs the REAL thing: two OS processes
joined by `jax.distributed` with gloo CPU collectives, each packing only
its own host block (tests/multihost_worker.py). Reference being matched:
the server tree spans hosts by construction (doc/design.md:204-220)."""

import numpy as np
import jax
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.parallel import (
    make_sharded_solver,
    pack_process_edges,
)
from doorman_tpu.parallel.multihost import (
    make_multihost_mesh,
    pad_edge_block,
    split_edges_by_host,
)
from doorman_tpu.parallel.sharded import replicate_resources
from doorman_tpu.solver.kernels import EdgeBatch, ResourceBatch, solve_tick


def edge_world(E=96, R=12, seed=0):
    rng = np.random.default_rng(seed)
    rid = np.sort(rng.integers(0, R, E).astype(np.int32))
    edges = EdgeBatch(
        resource=rid,
        wants=rng.integers(0, 100, E).astype(np.float64),
        has=rng.integers(0, 50, E).astype(np.float64),
        subclients=np.ones(E),
        active=np.ones(E, bool),
    )
    resources = ResourceBatch(
        capacity=rng.integers(100, 5000, R).astype(np.float64),
        algo_kind=rng.integers(0, 5, R).astype(np.int32),
        learning=np.zeros(R, bool),
        static_capacity=rng.integers(1, 100, R).astype(np.float64),
    )
    return edges, resources


def test_pad_edge_block_math():
    edges, _ = edge_world(E=10)
    block = pad_edge_block(edges, 16)
    assert np.asarray(block.active).shape == (16,)
    assert np.asarray(block.active)[10:].sum() == 0  # padding inactive
    assert (np.asarray(block.wants)[10:] == 0).all()
    # Fill rid repeats the last id: the block stays sorted by segment.
    rid = np.asarray(block.resource)
    assert (np.diff(rid) >= 0).all()
    assert (rid[10:] == rid[9]).all()
    # Exact-size block is the identity.
    same = pad_edge_block(edges, 10)
    np.testing.assert_array_equal(np.asarray(same.wants),
                                  np.asarray(edges.wants))
    with pytest.raises(ValueError):
        pad_edge_block(edges, 9)


def test_split_then_concat_is_identity():
    edges, _ = edge_world(E=97)  # deliberately not divisible
    parts = split_edges_by_host(edges, 4)
    assert sum(np.asarray(p.active).shape[0] for p in parts) == 97
    for field in ("resource", "wants", "has", "subclients", "active"):
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(getattr(p, field)) for p in parts]),
            np.asarray(getattr(edges, field)),
        )


def test_multihost_mesh_layout_follows_process_blocks():
    devices = jax.devices("cpu")[:8]
    mesh = make_multihost_mesh(("dc", "clients"), devices)
    # Single process: one dc block holding all its chips, in id order.
    assert dict(mesh.shape) == {"dc": 1, "clients": 8}
    flat = list(mesh.devices.flat)
    assert [d.id for d in flat] == sorted(d.id for d in flat)
    single = make_multihost_mesh(("clients",), devices)
    assert dict(single.shape) == {"clients": 8}


def test_pack_process_edges_solves_to_single_device_result():
    """The host-local packing path end-to-end: pad to the per-host
    block, assemble via make_array_from_process_local_data, solve
    sharded, compare with the unsharded solve."""
    devices = jax.devices("cpu")[:8]
    mesh = make_multihost_mesh(("dc", "clients"), devices)
    edges, resources = edge_world(E=90, R=11, seed=3)
    # Per-host block of 96 (> 90: exercises the inactive padding).
    packed = pack_process_edges(mesh, edges, edges_per_host=96)
    assert np.asarray(packed.active).shape == (96,)

    solve = make_sharded_solver(mesh)
    gets = np.asarray(
        jax.block_until_ready(
            solve(packed, replicate_resources(mesh, resources))
        )
    )
    expected = np.asarray(jax.jit(solve_tick)(edges, resources))
    np.testing.assert_allclose(gets[:90], expected, rtol=1e-12, atol=1e-12)
    assert (gets[90:] == 0).all()  # padded edges granted nothing


def test_initialize_wires_env_fallbacks(monkeypatch):
    """initialize() plumbs DOORMAN_* env into jax.distributed.initialize
    (the real call needs a live coordinator, so record the arguments);
    without a coordinator configured it must be a no-op."""
    from doorman_tpu.parallel import multihost

    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: calls.append(kw),
    )
    monkeypatch.setattr(multihost, "_initialized", False)

    # No coordinator anywhere: single-host no-op.
    monkeypatch.delenv("DOORMAN_COORDINATOR", raising=False)
    multihost.initialize()
    assert calls == []

    monkeypatch.setenv("DOORMAN_COORDINATOR", "10.0.0.1:1234")
    monkeypatch.setenv("DOORMAN_NUM_PROCESSES", "4")
    monkeypatch.setenv("DOORMAN_PROCESS_ID", "2")
    multihost.initialize()
    assert calls == [
        {
            "coordinator_address": "10.0.0.1:1234",
            "num_processes": 4,
            "process_id": 2,
            "local_device_ids": None,
        }
    ]
    # Idempotent: a second call does not re-initialize.
    multihost.initialize()
    assert len(calls) == 1

    # Explicit arguments win over env.
    monkeypatch.setattr(multihost, "_initialized", False)
    multihost.initialize(
        coordinator_address="h:9", num_processes=2, process_id=1
    )
    assert calls[-1]["coordinator_address"] == "h:9"
    assert calls[-1]["num_processes"] == 2


def test_two_process_distributed_solve_over_gloo():
    """The REAL multi-process path: two OS processes, each owning 2
    virtual CPU devices and only ITS half of the edge table, joined by
    `multihost.initialize` (DOORMAN_* env wiring) with gloo collectives.
    Each worker packs host-locally, runs the sharded solve over the
    process-ordered mesh, and compares its addressable shards against
    the single-device full-table oracle (tests/multihost_worker.py).
    This is the composition the single-process unit tests above can
    only simulate."""
    import os
    import socket
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    # The worker pins its own JAX/XLA setup; drop the pytest session's.
    base = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("JAX_", "XLA_"))
    }

    def run_once():
        """One spawn/reap cycle. Returns [(returncode, output), ...];
        every child is reaped (kill + communicate) on every path so a
        hung or half-spawned pair never outlives the test."""
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()  # bind-then-close: small reuse race, retried below
        procs = []
        try:
            for pid in range(2):
                procs.append(
                    subprocess.Popen(
                        [sys.executable, worker],
                        env=dict(
                            base,
                            DOORMAN_COORDINATOR=f"127.0.0.1:{port}",
                            DOORMAN_NUM_PROCESSES="2",
                            DOORMAN_PROCESS_ID=str(pid),
                        ),
                        stdout=subprocess.PIPE,
                        stderr=subprocess.STDOUT,
                        text=True,
                    )
                )
            return [list(p.communicate(timeout=240)) + [p.returncode]
                    for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()  # reap; drain the diagnostics pipe

    results = run_once()
    if any(rc != 0 for _, _, rc in results):
        # The ephemeral coordinator port can be stolen between probe and
        # bind (TOCTOU); one retry with a fresh port covers that flake.
        results = run_once()
    for pid, (out, _, rc) in enumerate(results):
        assert rc == 0, f"worker {pid} failed:\n{out}"
        assert "MULTIHOST WORKER OK" in out, out
