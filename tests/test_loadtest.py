"""Recipe parser/schedule tests (capability parity with reference
go/client/recipe/recipe.go) and a short end-to-end loadtest: server +
target + recipe-driven workers over real gRPC/TCP on loopback."""

import asyncio
import math
import random

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.loadtest import RecipeError, parse_recipes
from doorman_tpu.loadtest.target import Target, ping
from doorman_tpu.loadtest.worker import run_worker
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_parse_recipes_counts_and_base():
    workers = parse_recipes("5x100+sin(30),2x10+constant_increase(1)")
    assert len(workers) == 7
    assert workers[0].recipe.name == "sin"
    assert workers[0].current_qps == 100.0
    assert workers[5].recipe.name == "constant_increase"
    assert workers[5].current_qps == 10.0
    # Workers of one recipe share the (frozen) recipe object.
    assert workers[0].recipe is workers[4].recipe


def test_parse_recipes_rejects_garbage():
    for bad in ["", "5x100", "5x100+nope(1)", "x100+sin(1)",
                "5x100+sin(1,2)", "5x100+sin()"]:
        with pytest.raises(RecipeError):
            parse_recipes(bad)


def test_constant_increase_schedule():
    clock = FakeClock()
    (w,) = parse_recipes(
        "1x100+constant_increase(5)", interval=60, reset=3600, clock=clock
    )
    assert not w.interval_expired()  # nothing elapsed
    clock.now = 61
    assert w.interval_expired()
    assert w.current_qps == 105.0 and w.old_qps == 100.0
    assert not w.interval_expired()  # same interval
    clock.now = 122
    assert w.interval_expired()
    assert w.current_qps == 110.0


def test_reset_snaps_back_to_base():
    clock = FakeClock()
    (w,) = parse_recipes(
        "1x10+constant_increase(10)", interval=1, reset=5, clock=clock
    )
    for t in (1.1, 2.2, 3.3):
        clock.now = t
        assert w.interval_expired()
    assert w.current_qps == 40.0
    clock.now = 5.5  # reset elapsed
    assert w.interval_expired()
    assert w.current_qps == 10.0
    assert w.reset_count == 1


def test_sin_and_inc_sin_shapes():
    clock = FakeClock()
    reset = 100.0
    (s,) = parse_recipes("1x0+sin(80)", interval=1, reset=reset, clock=clock)
    (i,) = parse_recipes(
        "1x0+inc_sin(80)", interval=1, reset=reset, clock=clock
    )
    clock.now = 50.0  # mid-reset: sin(pi/2) = 1
    assert s.interval_expired()
    assert s.current_qps == pytest.approx(80.0)
    assert i.interval_expired()
    assert i.current_qps == pytest.approx(0.0)  # no reset yet: factor 0
    clock.now = 101.0
    assert i.interval_expired()  # the reset: back to base
    clock.now = 151.0  # mid second cycle, reset_count == 1
    assert i.interval_expired()
    assert i.current_qps == pytest.approx(
        1 * 80.0 * math.sin(math.pi * 50.0 / reset)
    )


def test_random_change_bounded():
    clock = FakeClock()
    (w,) = parse_recipes(
        "1x100+random_change(20)", interval=1, reset=10_000, clock=clock,
        rng=random.Random(3),
    )
    for k in range(50):
        clock.now = (k + 1) * 1.01
        assert w.interval_expired()
        assert 80.0 <= w.current_qps <= 120.0


def test_target_counts_requests():
    async def body():
        target = Target()
        port = await target.start(0)
        call, close = await ping("127.0.0.1", port)
        for _ in range(7):
            await call()
        assert target.requests == 7
        await close()
        await target.stop()

    asyncio.run(body())


def test_loadtest_end_to_end():
    """Two recipe workers against a real server and target: requests flow
    and the server sees the demand."""
    config = """
resources:
- identifier_glob: "*"
  capacity: 1000
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
"""

    async def body():
        server = CapacityServer(
            "lt-server", TrivialElection(), minimum_refresh_interval=0.0
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(config))
        await asyncio.sleep(0)
        server.current_master = f"127.0.0.1:{port}"

        target = Target()
        tport = await target.start(0)

        workers = parse_recipes(
            "2x50+constant_increase(0)", interval=3600, reset=7200
        )
        stats = {}
        tasks = [
            asyncio.create_task(
                run_worker(
                    i, w, f"127.0.0.1:{port}", f"lt-{i}", "shared",
                    f"127.0.0.1:{tport}", stats,
                    minimum_refresh_interval=0.0,
                )
            )
            for i, w in enumerate(workers)
        ]
        # ~1.5s of load at 2x50 qps should produce a healthy batch of
        # requests through the limiter.
        await asyncio.sleep(1.5)
        res = server.resources.get("shared")
        assert res is not None
        # Demand visible while workers hold leases (released on cancel).
        assert res.store.sum_wants == pytest.approx(100.0)

        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

        assert target.requests > 20, target.requests

        await target.stop()
        await server.stop()

    asyncio.run(body())


# ----------------------------------------------------------------------
# Rate curves (the schedule shared by storm --rate-curve and the
# workload harness's diurnal generator)
# ----------------------------------------------------------------------


def test_rate_curve_parse_interpolate_and_integrate():
    from doorman_tpu.loadtest.ratecurve import RateCurve

    curve = RateCurve.parse("0:10,30:45,60:0")
    assert curve.rate_at(0) == 10.0
    assert curve.rate_at(15) == 27.5  # linear between knots
    assert curve.rate_at(-5) == 10.0  # clamped before the first knot
    assert curve.rate_at(90) == 0.0   # clamped after the last
    # Trapezoid over the whole span: (10+45)/2*30 + 45/2*30 = 1500.
    assert curve.integral(0, 60) == pytest.approx(1500.0)
    assert curve.end_time == 60.0


def test_rate_curve_rejects_garbage():
    from doorman_tpu.loadtest.ratecurve import RateCurve

    for bad in ("", "abc", "0:10,5", "10:5,0:10", "0:-3"):
        with pytest.raises(ValueError):
            RateCurve.parse(bad)


def test_arrival_sampler_is_deterministic_and_tracks_the_curve():
    from doorman_tpu.loadtest.ratecurve import ArrivalSampler, RateCurve

    curve = RateCurve.parse("0:5,10:5")
    a = ArrivalSampler(curve, jitter=0.3, rng=random.Random(11))
    b = ArrivalSampler(curve, jitter=0.3, rng=random.Random(11))
    counts_a = [a.take(t, t + 1.0) for t in range(10)]
    counts_b = [b.take(t, t + 1.0) for t in range(10)]
    assert counts_a == counts_b  # seeded replay
    # Fractional carry: the total tracks the integral despite jitter.
    assert sum(counts_a) == pytest.approx(50, abs=50 * 0.35)


def test_arrival_sampler_wraps_periodic_curves():
    from doorman_tpu.loadtest.ratecurve import ArrivalSampler, RateCurve

    curve = RateCurve.parse("0:0,5:10,10:0")
    s = ArrivalSampler(curve, jitter=0.0, rng=random.Random(0),
                       period=10.0)
    first = [s.take(t, t + 1.0) for t in range(10)]
    second = [s.take(10 + t, 11 + t) for t in range(10)]
    assert sum(first) == sum(second)  # one full period each


def test_storm_parser_accepts_rate_curve_flags():
    from doorman_tpu.loadtest.storm import make_parser

    args = make_parser().parse_args([
        "--server", "x:1", "--rate-curve", "0:10,30:45", "--rate-jitter",
        "0.1", "--seed", "3",
    ])
    assert args.rate_curve == "0:10,30:45"
    assert args.rate_jitter == 0.1
    assert args.seed == 3


def test_storm_rejects_rate_curve_with_streams():
    from doorman_tpu.loadtest.storm import run_storm

    with pytest.raises(ValueError, match="stream"):
        asyncio.run(run_storm(
            "127.0.0.1:1", workers=1, duration=0.1, stream=True,
            rate_curve="0:10,1:10",
        ))


def test_storm_parser_accepts_procs_flag():
    from doorman_tpu.loadtest.storm import make_parser

    args = make_parser().parse_args(["--procs", "4"])
    assert args.procs == 4
    assert make_parser().parse_args([]).procs == 1


def test_storm_merge_sums_counters_and_keeps_exact_tails():
    from doorman_tpu.loadtest.storm import (
        merge_storm_results,
        percentile,
    )

    def part(ok, shed, band_lat, dur):
        return {
            "ok": ok, "shed": shed, "errors": 0, "redirects": 1,
            "ok_by_band": {0: ok}, "shed_by_band": {0: shed},
            "workers": 2, "duration_s": dur,
            "latencies_sorted": sorted(band_lat),
            "latencies_sorted_by_band": {0: sorted(band_lat)},
        }

    a = part(3, 1, [0.010, 0.020, 0.030], 5.0)
    b = part(5, 2, [0.001, 0.002, 0.003, 0.004, 0.005], 5.2)
    merged = merge_storm_results([a, b])
    assert merged["procs"] == 2 and merged["workers"] == 4
    assert merged["ok"] == 8 and merged["shed"] == 3
    assert merged["redirects"] == 2
    assert merged["ok_by_band"] == {0: 8}
    # The procs ran concurrently: rates divide by the slowest child's
    # wall, not the sum of the two.
    assert merged["duration_s"] == 5.2
    assert merged["goodput_qps"] == round(8 / 5.2, 1)
    # Percentiles come from the CONCATENATED population — exact, not
    # an average of the per-proc percentiles.
    population = sorted(
        a["latencies_sorted"] + b["latencies_sorted"]
    )
    assert merged["p99_s"] == round(percentile(population, 0.99), 6)
    assert merged["p50_s"] == round(percentile(population, 0.50), 6)
    assert merged["p99_s_by_band"][0] == merged["p99_s"]
    with pytest.raises(ValueError, match="no storm results"):
        merge_storm_results([])


def test_storm_procs_single_proc_falls_through_inline():
    # procs=1 takes the in-process path (no spawn): against a dead
    # address everything errors but the report shape is the merged one.
    from doorman_tpu.loadtest.storm import run_storm_procs

    out = run_storm_procs(
        "127.0.0.1:1", procs=1, workers=2, duration=0.2,
        rpc_timeout=0.05,
    )
    assert out["procs"] == 1 and out["workers"] == 2
    assert out["ok"] == 0 and out["errors"] > 0
