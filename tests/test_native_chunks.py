"""Engine-level wide-resource (chunked) tracking: slot-granular dirty
lists, per-chunk membership versions, and the chunk pack/apply calls
(native/store.cc dm_chunk_* / dm_*_slots). The wide resident solver
(solver/resident_wide.py) is built on exactly these guarantees."""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)


def make_engine(n=20, W=8):
    eng = native.StoreEngine()
    st = eng.store("wide")
    for c in range(n):
        st.assign(f"c{c}", 60.0, 5.0, 0.0, float(c + 1), 1)
    eng.chunk_config(np.array([st._rid], np.int32), W)
    return eng, st


def test_pack_chunks_layout_and_fill():
    eng, st = make_engine(n=20, W=8)
    rid = st._rid
    w, h, s, a, filled, ver = eng.pack_chunks(
        np.array([rid] * 3, np.int32), np.arange(3, dtype=np.int32), 8
    )
    assert list(filled) == [8, 8, 4]
    assert list(ver) == [0, 0, 0]
    # Slot s lives at (chunk s // W, lane s % W), insertion order.
    np.testing.assert_array_equal(w[0], np.arange(1, 9))
    np.testing.assert_array_equal(w[2][:4], [17, 18, 19, 20])
    assert (a[2][4:] == 0).all() and (w[2][4:] == 0).all()


def test_slot_dirty_levels_and_drain():
    eng, st = make_engine()
    rid = st._rid
    # wants-only change -> level 1.
    st.assign("c5", 60.0, 5.0, 0.0, 99.0, 1)
    assert list(eng.dirty_slot_rids()) == [rid]
    slots, lvl = eng.drain_slots(rid)
    assert list(slots) == [5] and list(lvl) == [1]
    # Drain cleared it.
    assert len(eng.dirty_slot_rids()) == 0
    slots, lvl = eng.drain_slots(rid)
    assert len(slots) == 0
    # has change -> level 2 (full).
    st.assign("c5", 60.0, 5.0, 7.0, 99.0, 1)
    slots, lvl = eng.drain_slots(rid)
    assert list(slots) == [5] and list(lvl) == [2]
    # Grant delivery (regrant) does NOT dirty a slot.
    st.regrant("c5", 3.0)
    assert len(eng.dirty_slot_rids()) == 0


def test_slot_channel_independent_of_resource_channel():
    """The narrow resident solver drains per-resource dirt; the wide
    solver drains per-slot dirt. Draining one channel must not consume
    the other."""
    eng, st = make_engine()
    rid = st._rid
    eng.drain_dirty2()  # clear the population's marks
    eng.drain_slots(rid)
    st.assign("c3", 60.0, 5.0, 0.0, 55.0, 1)
    rids, _full = eng.drain_dirty2()
    assert list(rids) == [rid]
    # The slot channel still has it.
    slots, lvl = eng.drain_slots(rid)
    assert list(slots) == [3]
    # And vice versa: a new write, slot drain first.
    st.assign("c4", 60.0, 5.0, 0.0, 56.0, 1)
    slots, _ = eng.drain_slots(rid)
    assert list(slots) == [4]
    rids, _full = eng.drain_dirty2()
    assert list(rids) == [rid]


def test_release_marks_both_touched_slots_and_bumps_versions():
    eng, st = make_engine(n=20, W=8)
    rid = st._rid
    eng.drain_slots(rid)
    # Swap-remove slot 3: last slot (19) moves into 3; both chunks'
    # membership changed (chunk 0 and chunk 2).
    st.release("c3")
    slots, lvl = eng.drain_slots(rid)
    assert set(slots) == {3, 19} and (lvl == 2).all()
    ver = eng.chunk_versions(
        np.array([rid] * 3, np.int32), np.arange(3, dtype=np.int32)
    )
    assert list(ver) == [1, 0, 1]
    # The vacated slot packs as inactive zeros (that upload clears the
    # lane on device).
    pw, ph, ps, pa = eng.pack_slots(rid, np.array([19], np.int64))
    assert pa[0] == 0 and pw[0] == 0


def test_insert_bumps_only_its_chunk():
    eng, st = make_engine(n=20, W=8)
    rid = st._rid
    eng.drain_slots(rid)
    st.assign("new", 60.0, 5.0, 0.0, 1.0, 1)  # slot 20 -> chunk 2
    slots, lvl = eng.drain_slots(rid)
    assert list(slots) == [20] and list(lvl) == [2]
    ver = eng.chunk_versions(
        np.array([rid] * 3, np.int32), np.arange(3, dtype=np.int32)
    )
    assert list(ver) == [0, 0, 1]


def test_apply_chunks_version_guard():
    eng, st = make_engine(n=20, W=8)
    rid = st._rid
    rids = np.array([rid] * 3, np.int32)
    chunks = np.arange(3, dtype=np.int32)
    ver = eng.chunk_versions(rids, chunks)
    st.release("c3")  # bumps chunks 0 and 2
    grants = np.full((3, 8), 7.0)
    applied = eng.apply_chunks(
        rids, chunks, grants, np.zeros(3, np.uint8), ver
    )
    assert applied == 1  # only chunk 1 still matches
    assert st.get("c8").has == 7.0  # chunk 1, slot 8
    assert st.get("c0").has == 0.0  # chunk 0 skipped
    # keep_has preserves even matching chunks (learning replay).
    ver = eng.chunk_versions(rids, chunks)
    applied = eng.apply_chunks(
        rids, chunks, grants * 0 + 9.0, np.ones(3, np.uint8), ver
    )
    assert applied == 3
    assert st.get("c8").has == 7.0


def test_apply_chunks_keeps_running_sums_consistent():
    eng, st = make_engine(n=20, W=8)
    rid = st._rid
    rids = np.array([rid] * 3, np.int32)
    chunks = np.arange(3, dtype=np.int32)
    ver = eng.chunk_versions(rids, chunks)
    grants = np.tile(np.arange(8, dtype=np.float64), (3, 1))
    eng.apply_chunks(rids, chunks, grants, np.zeros(3, np.uint8), ver)
    expected = sum(float(l.has) for _, l in st.items())
    assert st.sum_has == pytest.approx(expected)
    # Only 20 slots live: the last chunk's padding lanes wrote nothing.
    assert st.sum_has == pytest.approx(2 * sum(range(8)) + sum(range(4)))


def test_chunk_config_reset_clears_state():
    eng, st = make_engine(n=20, W=8)
    rid = st._rid
    st.assign("c2", 60.0, 5.0, 0.0, 77.0, 1)
    # Reconfigure (e.g. a rebuild with a new width): dirt and versions
    # reset; the caller repacks everything immediately after.
    eng.chunk_config(np.array([rid], np.int32), 16)
    assert len(eng.dirty_slot_rids()) == 0
    ver = eng.chunk_versions(
        np.array([rid] * 2, np.int32), np.arange(2, dtype=np.int32)
    )
    assert list(ver) == [0, 0]


def test_untracked_resources_cost_nothing():
    eng = native.StoreEngine()
    st = eng.store("narrow")
    for c in range(5):
        st.assign(f"c{c}", 60.0, 5.0, 0.0, 1.0, 1)
    # No chunk_config: writes must not accumulate slot dirt.
    st.assign("c0", 60.0, 5.0, 0.0, 2.0, 1)
    assert len(eng.dirty_slot_rids()) == 0
    slots, _ = eng.drain_slots(st._rid)
    assert len(slots) == 0
