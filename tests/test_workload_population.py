"""Vector population engine (workload.population): the parity pin —
at small scale the array-backed engine must produce a byte-identical
event log (log_sha256) to the per-client path — plus the batched
seams it rides on (grouped establishment order, admit_many draw
equivalence, forecaster warm-start bit-identity, trace replay)."""

import asyncio
import json
import random

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.workload.harness import WorkloadRunner
from doorman_tpu.workload.spec import GeneratorSpec, WorkloadSpec

G = GeneratorSpec.make


def run(coro):
    return asyncio.run(coro)


def churn_spec(engine: str, seed: int, native_store: bool
               ) -> WorkloadSpec:
    """The churny parity workload: diurnal arrivals/departures,
    multi-region RTT, elastic preemption, AIMD admission — every
    mutator and draw surface the vector engine must replay exactly."""
    return WorkloadSpec.make(
        "churn", 24, seed=seed, capacity=120.0,
        algorithm="PROPORTIONAL_SHARE", safe_capacity=2.0,
        lease_length=4.0, native_store=native_store,
        admission={"max_rps": 40.0, "min_level": 0.05},
        base_clients=((0, 10.0), (1, 12.0), (2, 8.0)),
        generators=(
            G("diurnal", curve="0:2,8:6,16:2", period=16.0, jitter=0.2,
              bands=[[0, 1.0], [1, 1.0]], wants=6.0, lifetime_ticks=5,
              max_population=40),
            G("multi_region",
              regions=[["local", 2.0, 2.0], ["far", 150.0, 1.0]]),
            G("elastic", jobs=3, total_work=90.0, min_wants=4.0),
        ),
        population_engine=engine,
    )


def fed_spec(engine: str, seed: int) -> WorkloadSpec:
    """Federated two-shard topology under a rolling deploy: sticky
    redirect chasing, one-tick mastership blindness, and the fed
    pointer walk all in play."""
    return WorkloadSpec.make(
        "fed", 26, seed=seed, servers=2, capacity=200.0,
        lease_length=3.0, election_ttl=2.0,
        federated={"straddle": ["r0"], "client_shards": [0, 0, 1, 1]},
        base_clients=((0, 20.0), (1, 10.0), (0, 20.0), (1, 10.0)),
        generators=(
            G("flash_crowd", at=6, duration=5, clients=8, band=0,
              wants=15.0),
            G("rolling_deploy", at=12, down_ticks=2, gap_ticks=4),
        ),
        population_engine=engine,
    )


def _run_spec(spec: WorkloadSpec):
    runner = WorkloadRunner(spec)
    verdict = run(runner.run())
    return verdict, runner


# ----------------------------------------------------------------------
# The parity pin
# ----------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1])
def test_vector_engine_parity_churn(seed):
    """Byte-identical log_sha256, vector vs per-client, churny spec."""
    ref, _ = _run_spec(churn_spec("clients", seed, False))
    vec, runner = _run_spec(churn_spec("vector", seed, False))
    assert vec["log_sha256"] == ref["log_sha256"]
    # The pin must exercise the ARRAY decide path, not just the
    # sequential fallback dressed in arrays.
    assert runner._vector.fast_rows_total > 0


@pytest.mark.skipif(
    not native.native_available(), reason="native store unavailable"
)
def test_vector_engine_parity_churn_native_store():
    """Same pin through the native C++ store: cross-store and
    cross-engine byte-identity in one comparison."""
    ref, _ = _run_spec(churn_spec("clients", 0, True))
    vec, _ = _run_spec(churn_spec("vector", 0, True))
    assert vec["log_sha256"] == ref["log_sha256"]
    # The native store changes the engine, not the log: the python
    # store's run hashes identically (the repo's standing discipline).
    py, _ = _run_spec(churn_spec("clients", 0, False))
    assert py["log_sha256"] == ref["log_sha256"]


def test_vector_engine_parity_federated_deploy():
    """Parity through shard redirects and a mastership flip: the
    sticky-chase replay (conn column) must reproduce the per-client
    connection's parked-server behavior, including the one-tick
    MasterUnknown blindness at the abdication tick."""
    ref, _ = _run_spec(fed_spec("clients", 0))
    vec, _ = _run_spec(fed_spec("vector", 0))
    assert vec["log_sha256"] == ref["log_sha256"]


# ----------------------------------------------------------------------
# Grouped establishment order (population-engine-independent)
# ----------------------------------------------------------------------


PROP_CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  safe_capacity: 2
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
"""


async def _prop_server(clock):
    from doorman_tpu.server.config import parse_yaml_config
    from doorman_tpu.server.election import TrivialElection
    from doorman_tpu.server.server import CapacityServer

    server = CapacityServer(
        "pop-test", TrivialElection(), mode="immediate",
        minimum_refresh_interval=0.0, clock=clock,
    )
    port = await server.start(0, host="127.0.0.1")
    await server.load_config(parse_yaml_config(PROP_CONFIG))
    await asyncio.sleep(0)
    server.current_master = f"127.0.0.1:{port}"
    return server


def test_decide_bulk_matches_per_request_establishment_order():
    """The canonical per-resource establishment order: decide_bulk's
    batch and a per-request _decide loop in the same arrival order
    leave two fresh servers with identical stores and grants."""
    from doorman_tpu.algorithms import Request

    async def body():
        # One shared frozen clock: expiry stamps must agree exactly.
        clock = FakeClock(1000.0)
        bulk_srv = await _prop_server(clock)
        seq_srv = await _prop_server(clock)
        try:
            cids = [f"c{i}" for i in range(6)]
            wants = np.array([30.0, 10.0, 25.0, 40.0, 5.0, 20.0])
            prios = np.zeros(6, np.int64)
            zeros = np.zeros(6)
            grants, expiry, refresh, safe, fast = (
                bulk_srv.decide_bulk(
                    "r0", cids, zeros, wants, prios,
                    old_has=zeros, old_wants=zeros,
                    new_mask=np.ones(6, bool), expected_count=0,
                )
            )
            seq = [
                seq_srv._decide(
                    "r0", Request(cid, 0.0, float(w), 1, priority=0)
                )[0]
                for cid, w in zip(cids, wants)
            ]
            assert list(grants) == [lease.has for lease in seq]
            assert list(expiry) == [lease.expiry for lease in seq]
            bulk_rows = sorted(
                bulk_srv.resources["r0"].store.dump_rows()
            )
            seq_rows = sorted(
                seq_srv.resources["r0"].store.dump_rows()
            )
            assert bulk_rows == seq_rows
            # A refresh batch over the established rows (non-new) must
            # agree too — the running-aggregate cumsum argument.
            wants2 = wants + 3.0
            grants2, _, _, _, _ = bulk_srv.decide_bulk(
                "r0", cids, grants, wants2, prios,
                old_has=grants, old_wants=wants,
                new_mask=np.zeros(6, bool), expected_count=6,
            )
            seq2 = [
                seq_srv._decide(
                    "r0",
                    Request(cid, float(h), float(w), 1, priority=0),
                )[0]
                for cid, h, w in zip(cids, grants, wants2)
            ]
            assert list(grants2) == [lease.has for lease in seq2]
            assert sorted(
                bulk_srv.resources["r0"].store.dump_rows()
            ) == sorted(seq_srv.resources["r0"].store.dump_rows())
        finally:
            await bulk_srv.stop()
            await seq_srv.stop()

    run(body())


# ----------------------------------------------------------------------
# Batched admission draws
# ----------------------------------------------------------------------


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _controller(seed):
    from doorman_tpu.admission.controller import AimdController

    return AimdController(
        window=1.0, clock=FakeClock(5.0), rng=random.Random(seed),
        max_rps=8.0, min_level=0.05,
    )


def test_admit_many_replays_the_sequential_draw_sequence():
    """admit_many == admit-loop: same mask, same RNG stream after —
    including unseen-band discovery mid-batch and hard-cap overflow."""
    # 30 arrivals vs max_rps 8: the tail crosses the hard cap; band 2
    # first appears at index 7 (a mid-batch band-set change).
    batch = [0, 1, 0, 0, 1, 1, 0, 2, 2, 0, 1] + [0, 1, 2] * 7
    for seed in (0, 3):
        a, b = _controller(seed), _controller(seed)
        loop_mask = [a.admit(p)[0] for p in batch]
        many_mask = b.admit_many(batch)
        assert list(many_mask) == loop_mask
        # Controllers fully converged: subsequent draws identical.
        follow_a = [a.admit(0)[0] for _ in range(10)]
        follow_b = [b.admit(0)[0] for _ in range(10)]
        assert follow_a == follow_b
        assert a.level == b.level


def test_check_get_capacity_many_matches_per_call_tallies():
    from doorman_tpu.admission import Admission

    batch = [1, 0, 0, 2, 1, 0] * 6
    one = Admission(controller=_controller(7))
    many = Admission(controller=_controller(7))
    loop_mask = [one.check_get_capacity_band(b) for b in batch]
    many_mask = many.check_get_capacity_many(batch)
    assert list(many_mask) == loop_mask
    assert one.tallies == many.tallies


# ----------------------------------------------------------------------
# Forecaster warm-start bit-identity (the --history-dir seam)
# ----------------------------------------------------------------------


def test_forecaster_warm_start_bit_identical_to_online(tmp_path):
    from doorman_tpu.obs.history import HistoryStore
    from doorman_tpu.workload.forecast import SeasonalForecaster

    offered = [12.0, 30.0, 7.0, 44.0, 19.0, 3.0, 28.0, 15.0]
    store = HistoryStore(str(tmp_path), component="workload:pin")
    for tick, v in enumerate(offered):
        store.append({"tick": tick, "offered": v})
    store.close()

    warm = SeasonalForecaster(series=2, period=4, alpha=0.25,
                              beta=0.5, engine="host")
    reopened = HistoryStore(str(tmp_path), component="workload:pin")
    fed = warm.warm_start(reopened, field="offered", interval=2.0)
    reopened.close()
    assert fed == len(offered)

    live = SeasonalForecaster(series=2, period=4, alpha=0.25,
                              beta=0.5, engine="host")
    last = None
    for v in offered:
        last = live.observe(np.full(2, np.float32(v / 2.0),
                                    np.float32))
    for got, want in zip(warm._state, live._state):
        assert np.array_equal(np.asarray(got), np.asarray(want))
    # And the next observation diverges nowhere: same forecast bits.
    nxt = np.full(2, np.float32(9.5), np.float32)
    assert np.array_equal(warm.observe(nxt), live.observe(nxt))
    assert last is not None


def test_run_scenario_history_dir_warm_starts_and_appends(tmp_path):
    from doorman_tpu.workload.scenarios import run_scenario

    first = run_scenario(
        "flash_crowd_predictive", scale=0.25, seed=0,
        history_dir=str(tmp_path),
    )
    assert first["forecaster_warm_start"] == 0
    second = run_scenario(
        "flash_crowd_predictive", scale=0.25, seed=0,
        history_dir=str(tmp_path),
    )
    # The second run primes from exactly the first run's tick records.
    assert second["forecaster_warm_start"] == first["ticks"]


# ----------------------------------------------------------------------
# Trace record/replay (the storm --record seam)
# ----------------------------------------------------------------------


def test_trace_generator_replays_events_deterministically(tmp_path):
    lines = [
        {"tick": 0, "band": 0, "wants": 10.0},
        {"tick": 0, "band": 1, "wants": 5.0},
        {"tick": 2, "band": 0, "wants": 7.5},
    ]
    path = tmp_path / "arrivals.jsonl"
    path.write_text(
        "".join(json.dumps(rec) + "\n" for rec in lines)
    )
    spec = WorkloadSpec.make(
        "trace_replay", 6, seed=0, capacity=100.0,
        generators=(
            G("trace", path=str(path), lifetime_ticks=2),
        ),
    )
    a, _ = _run_spec(spec)
    b, _ = _run_spec(spec)
    assert a["log_sha256"] == b["log_sha256"]
    arrive = [e for e in a["event_log"] if e[1] == "trace_arrive"]
    assert [(e[0], e[2]) for e in arrive] == [(0, 2), (2, 1)]


def test_trace_generator_inline_events_and_validation():
    from doorman_tpu.workload.generators import GENERATORS

    assert "trace" in GENERATORS
    with pytest.raises(ValueError, match="events or path"):
        GENERATORS["trace"]({})
    spec = WorkloadSpec.make(
        "trace_inline", 5, seed=1, capacity=50.0,
        generators=(
            G("trace", events=((1, 0, 8.0), (1, 1, 4.0)),
              lifetime_ticks=2),
        ),
    )
    v, _ = _run_spec(spec)
    arrive = [e for e in v["event_log"] if e[1] == "trace_arrive"]
    assert [(e[0], e[2]) for e in arrive] == [(1, 2)]


def test_storm_record_flags_and_stream_guard():
    from doorman_tpu.loadtest.storm import make_parser, run_storm

    args = make_parser().parse_args(
        ["--record", "/tmp/x.jsonl", "--record-tick", "0.5"]
    )
    assert args.record == "/tmp/x.jsonl"
    assert args.record_tick == 0.5
    assert make_parser().parse_args([]).record == ""
    with pytest.raises(ValueError, match="record"):
        run(run_storm(
            "127.0.0.1:1", workers=1, duration=0.1, stream=True,
            record=True,
        ))


def test_storm_merge_concatenates_arrival_logs():
    from doorman_tpu.loadtest.storm import merge_storm_results

    def part(arrivals):
        return {
            "ok": 1, "shed": 0, "errors": 0, "redirects": 0,
            "ok_by_band": {0: 1}, "shed_by_band": {},
            "workers": 1, "duration_s": 1.0,
            "latencies_sorted": [0.01],
            "latencies_sorted_by_band": {0: [0.01]},
            "arrivals": arrivals,
        }

    merged = merge_storm_results([
        part([[0.5, 0, 10.0], [0.1, 1, 5.0]]),
        part([[0.3, 0, 10.0]]),
    ])
    assert merged["arrivals"] == [
        [0.1, 1, 5.0], [0.3, 0, 10.0], [0.5, 0, 10.0],
    ]


# ----------------------------------------------------------------------
# Million-scenario registration (spec shape; runs live in tier1 smoke)
# ----------------------------------------------------------------------


@pytest.mark.parametrize(
    "name", ["diurnal_million", "flash_crowd_million"]
)
def test_million_scenarios_registered_at_scale(name):
    from doorman_tpu.workload.scenarios import SCENARIOS

    spec = SCENARIOS[name](scale=1.0, seed=0)
    assert spec.population_engine == "vector"
    assert spec.native_store
    assert sum(int(c) for c, _b, _w in spec.base_population) == 1_000_000
    # Leases must outlive a full deadline-wheel lap.
    assert spec.lease_length > spec.refresh_spread * spec.tick_interval
    assert spec.gate_targets()["peak_population"] >= 1_000_000


def test_million_scenario_smoke_at_small_scale():
    from doorman_tpu.workload.scenarios import run_scenario

    v = run_scenario("diurnal_million", scale=0.001, seed=0)
    assert v["ok"], v["slo"]["verdicts"]
    assert v["summary"]["peak_population"] >= 1000
