"""Churn-proportional scoped solve: byte identity, escalation, and the
scope-cache dispatch pins.

The scoped tick (solver/engine.py ScopeTracker + the fused-scoped
executables in resident.py / resident_wide.py) solves only the
resource-group closure of the dirty set plus the not-yet-converged
frontier, gathered into a pow2-bucketed compact table, and carries
every other unit's resident grants forward untouched. This suite pins
the claims that make it shippable:

  * byte identity: scoped vs full stores are IDENTICAL over seeded
    churn that mixes bf16-exact/non-exact wants, releases, new
    clients, learning flips and config-epoch bumps, across all four
    resident paths (narrow/wide x single-device/mesh), with the
    delta-tracking changed-rid stream — the streaming push's input —
    equal too;
  * escalation: every forced-full reason fires when its trigger does
    (rebuild, config-epoch, config-drift, expiry-sweep, round-trip,
    disabled, scope-reset) and `last_solve_mode`/`last_full_reason`
    record it;
  * accounting: a steady scoped tick costs 3 dispatches (fused buffer
    + scope buffer + launch) while the scope changes and falls back to
    the PR-13 2-dispatch floor when the scope repeats (the quiet-tick
    fixpoint: the scope index buffer is cached, never re-placed);
  * closure: a wide resource's scope spans ALL its straddling chunks
    from one dirty slot; mesh ticks carry per-shard scoped extents
    whose counts sum to the global scope.
"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.parallel import make_mesh
from doorman_tpu.solver.resident import ResidentDenseSolver
from doorman_tpu.solver.resident_wide import WideResidentSolver
from doorman_tpu.utils import dispatch as dispatch_mod
from tests.test_engine import assert_store_parity, conformance_churn
from tests.test_resident_solver import all_leases, make_world

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

SCOPED_PATHS = ("resident", "resident_mesh", "wide", "wide_mesh")


def _make(path, engine, clock, scoped, fused=True):
    mesh = make_mesh() if path.endswith("_mesh") else None
    if path.startswith("resident"):
        return ResidentDenseSolver(
            engine, dtype=np.float64, clock=clock, rotate_ticks=1,
            mesh=mesh, fused=fused, scoped=scoped,
        )
    return WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8, mesh=mesh, fused=fused, scoped=scoped,
    )


@pytest.mark.parametrize("path", SCOPED_PATHS)
def test_scoped_vs_full_byte_identity(path):
    """The load-bearing pin: one seeded churn stream (mixed bands of
    algo kinds via make_world, bf16-exact and non-exact wants,
    releases, new clients, a learning-mode flip with a config-epoch
    bump), scoped and full solvers compared store-for-store every
    tick. Narrow paths additionally run delta tracking and must emit
    the SAME changed-rid stream — the streaming push fans out from
    exactly this set, so equal rids pin the push sequence unchanged."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    full = _make(path, eng_a, clock, scoped=False)
    scoped = _make(path, eng_b, clock, scoped=True)
    assert scoped.scoped_solve and not full.scoped_solve
    track = path.startswith("resident")
    if track:
        assert full.enable_delta_tracking()
        assert scoped.enable_delta_tracking()
    rng_a, rng_b = (np.random.default_rng(17) for _ in range(2))
    scoped_ran = 0
    for step in range(10):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        if step == 4:
            # Learning-mode flip mid-run: the epoch bump must escalate
            # the scoped path to a full solve, loudly.
            res_a[2].learning_mode_end = t[0] + 2.5
            res_b[2].learning_mode_end = t[0] + 2.5
        epoch = 1 if step >= 4 else 0
        full.step(res_a, epoch)
        scoped.step(res_b, epoch)
        ref, got = all_leases(res_a), all_leases(res_b)
        # Scoped vs full is exact on EVERY path (the compact solve
        # runs the same per-unit ops over the same values; unscoped
        # units are carried, not recomputed) — the wide paths'
        # reassociation tolerance applies vs the BatchSolver, not
        # here.
        assert ref.keys() == got.keys(), f"{path} step {step}"
        for key in ref:
            assert got[key] == ref[key], (
                f"{path} step {step} lease {key}: "
                f"{got[key]} != {ref[key]}"
            )
        if track:
            assert (
                sorted(full.take_changed_rids())
                == sorted(scoped.take_changed_rids())
            ), f"{path} step {step}: changed-rid streams diverged"
        if scoped.last_solve_mode == "scoped":
            scoped_ran += 1
        t[0] += 1.0
    # The scoped executable actually ran (not everything escalated),
    # and the full reference never ran scoped.
    assert scoped_ran >= 5, scoped.solve_modes
    assert full.solve_modes["scoped"] == 0
    scoped_keys = [
        k for k in scoped._tick_fns if "scoped" in str(k[0])
    ]
    assert scoped_keys, "no scoped executable compiled"


def test_scoped_matches_batch_ground_truth():
    """Scoped narrow stores also match the BatchSolver oracle world, so
    the scoped path cannot drift from the reference math even if both
    resident modes drifted together."""
    from doorman_tpu.solver.batch import BatchSolver
    from doorman_tpu.solver.engine import BatchTickAdapter

    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    batch = BatchTickAdapter(BatchSolver(dtype=np.float64, clock=clock))
    scoped = _make("resident", eng_b, clock, scoped=True)
    rng_a, rng_b = (np.random.default_rng(23) for _ in range(2))
    for step in range(6):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        batch.step(res_a, 0)
        scoped.step(res_b, 0)
        assert_store_parity(
            all_leases(res_a), all_leases(res_b), "resident",
            f"step {step}",
        )
        t[0] += 1.0
    assert scoped.solve_modes["scoped"] >= 4


def test_forced_full_escalation_reasons():
    """Each escalation trigger fires its documented reason (the
    forced-full reasons table, doc/operations.md) and the tick solves
    full; steady ticks in between run scoped."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    solver = _make("resident", engine, clock, scoped=True)

    def tick(epoch=0):
        solver.step(resources, epoch)
        t[0] += 1.0
        return solver.last_solve_mode, solver.last_full_reason

    # First tick: rebuild.
    assert tick() == ("full", "rebuild")
    # Steady dirty tick: scoped.
    resources[0].store.assign("c0_0", 60.0, 5.0, 0.0, 17.0, 1)
    assert tick() == ("scoped", None)
    # Config-epoch bump: templates re-read.
    assert tick(epoch=1) == ("full", "config-epoch")
    # Learning window installed WITH an epoch bump (the mirror re-read
    # sees it); the flip when TIME passes the window end — no epoch
    # movement — is the time-driven config-drift escalation.
    resources[3].learning_mode_end = t[0] + 1.5
    assert tick(epoch=2) == ("full", "config-epoch")
    assert tick(epoch=2)[0] == "scoped"  # inside the window: steady
    assert tick(epoch=2) == ("full", "config-drift")  # window ended
    # Expiry sweep: a lease the sweep removes without naming its row.
    resources[5].store.assign("dying", 0.5, 0.5, 0.0, 3.0, 1)
    t[0] += 2.0
    assert tick(epoch=2) == ("full", "expiry-sweep")
    # Membership change (new resource list) forces a rebuild.
    engine2, resources2 = make_world(clock, n_res=13)
    solver2 = _make("resident", engine2, clock, scoped=True)
    solver2.step(resources2[:12], 0)
    assert solver2.last_full_reason == "rebuild"
    solver2.step(resources2, 0)
    assert (
        solver2.last_solve_mode,
        solver2.last_full_reason,
    ) == ("full", "rebuild")
    # Runtime toggle off -> "disabled"; back on -> one "scope-reset".
    solver.scoped_solve = False
    assert tick(epoch=2) == ("full", "disabled")
    solver.scoped_solve = True
    assert tick(epoch=2) == ("full", "scope-reset")
    resources[0].store.assign("c0_0", 60.0, 5.0, 0.0, 19.0, 1)
    assert tick(epoch=2) == ("scoped", None)


def test_round_trip_mode_never_scopes():
    """fused=False (the triage baseline) records the round-trip
    reason and produces identical stores anyway."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    solver = _make("resident", engine, clock, scoped=True, fused=False)
    rng = np.random.default_rng(3)
    for step in range(3):
        conformance_churn(resources, step, rng)
        solver.step(resources, 0)
        t[0] += 1.0
    assert solver.solve_modes["scoped"] == 0
    assert solver.last_full_reason == "round-trip"


def test_scope_cache_dispatch_counts():
    """The scope-buffer cache pin (the PR-13-style dispatch-count
    test): a steady tracked scoped tick costs 3 dispatches (fused
    buffer + scope buffer + launch) and 1 host sync while the scope
    CHANGES; when the same dirty set repeats — the quiet-tick fixpoint
    producing a byte-identical scope vector — the cached scope buffer
    is NOT re-placed and the tick is back at the 2-dispatch fused
    floor."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    solver = _make("resident", engine, clock, scoped=True)
    solver.enable_delta_tracking()
    rng = np.random.default_rng(5)
    for step in range(4):  # build + settle the executables
        conformance_churn(resources, step, rng)
        solver.step(resources, 0)
        t[0] += 1.0

    def dirty(i, wants):
        resources[i].store.assign(
            f"c{i}_0", 60.0, 5.0,
            resources[i].store.get(f"c{i}_0").has, wants, 1,
        )

    # Drain the frontier to empty so the scope is exactly the dirty
    # row (quiet ticks retire converged rows through the moved mask).
    for _ in range(6):
        solver.step(resources, 0)
        t[0] += 1.0

    # Same dirty row, same wants value twice: after the first tick
    # establishes the scope (and its frontier entry keeps the row in
    # scope), the second tick's scope vector is byte-identical and the
    # cache must serve it.
    dirty(0, 21.0)
    solver.step(resources, 0)
    t[0] += 1.0
    dirty(0, 22.0)
    before = dispatch_mod.snapshot()
    solver.step(resources, 0)
    cached = dispatch_mod.delta(before)
    t[0] += 1.0
    assert solver.last_solve_mode == "scoped"
    assert cached["dispatches"] == 2, cached
    assert cached["host_syncs"] == 1, cached

    # A DIFFERENT row dirties: the scope vector changes, costing the
    # one extra scope-buffer placement.
    dirty(7, 33.0)
    before = dispatch_mod.snapshot()
    solver.step(resources, 0)
    moved = dispatch_mod.delta(before)
    t[0] += 1.0
    assert solver.last_solve_mode == "scoped"
    assert moved["dispatches"] == 3, moved
    assert moved["host_syncs"] == 1, moved


def test_quiet_ticks_shrink_scope_to_fixpoint():
    """After churn stops, the frontier drains through the moved-mask
    feedback down to its floor — the rows the full solve itself never
    stops moving (PROPORTIONAL_SHARE's `min(scaled, free)` can cycle
    at the ULP, and the scoped tick replays the full solve's
    iteration bit-for-bit) — and the scope then REPEATS byte-identically
    tick over tick, which is what the scope-buffer cache and the
    2-dispatch quiet-tick pin ride on. A FAIR_SHARE-only world (its
    level depends on wants, not has) converges bitwise and drains the
    frontier to exactly zero."""
    from doorman_tpu.core.resource import Resource
    from doorman_tpu.proto import doorman_pb2 as pb

    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    # Mixed world: the frontier must shrink and stabilize.
    engine, resources = make_world(clock)
    solver = _make("resident", engine, clock, scoped=True)
    rng = np.random.default_rng(7)
    for step in range(3):
        conformance_churn(resources, step, rng)
        solver.step(resources, 0)
        t[0] += 1.0
    sizes = []
    for _ in range(8):
        solver.step(resources, 0)
        if solver.last_solve_mode == "scoped":
            sizes.append(solver.last_scope["rows"])
        t[0] += 1.0
    assert sizes[-1] <= max(2, sizes[0]), sizes
    assert sizes[-1] == sizes[-2] == sizes[-3], sizes  # stable floor

    # Fair-share world: exact bitwise convergence, frontier -> empty.
    eng2 = native.StoreEngine(clock=clock)
    res2 = []
    for r in range(6):
        tpl = pb.ResourceTemplate(
            identifier_glob=f"fair{r}", capacity=100.0,
            algorithm=pb.Algorithm(
                kind=pb.Algorithm.FAIR_SHARE,
                lease_length=60, refresh_interval=5,
            ),
        )
        res = Resource(
            f"fair{r}", tpl, clock=clock, store_factory=eng2.store
        )
        for c in range(5):
            res.store.assign(f"f{r}_{c}", 60.0, 5.0, 0.0, 30.0 + c, 1)
        res2.append(res)
    solver2 = _make("resident", eng2, clock, scoped=True)
    for _ in range(6):
        solver2.step(res2, 0)
        t[0] += 1.0
    assert len(solver2._scope) == 0
    assert solver2.last_scope == {"rows": 0, "resources": 0}


def test_pow2_bucket_boundaries_compile_bounded():
    """Scope sizes crossing a pow2 boundary compile a new executable;
    sizes within a bucket reuse it (the recompile count stays
    O(log R))."""
    from doorman_tpu.solver.engine import pow2_bucket

    assert pow2_bucket(0) == 8
    assert pow2_bucket(8) == 8
    assert pow2_bucket(9) == 16
    assert pow2_bucket(17, 8) == 32
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    solver = _make("resident", engine, clock, scoped=True)
    solver.step(resources, 0)
    t[0] += 1.0

    def scoped_cbs():
        return {
            k[4]
            for k in solver._tick_fns
            if str(k[0]) == "fused_scoped"
        }

    # The post-rebuild frontier covers all 12 rows -> the 16 bucket;
    # once it drains, small dirty sets ride the 8 bucket; a mid-bucket
    # size reuses the executable (no new key).
    solver.step(resources, 0)
    t[0] += 1.0
    assert 16 in scoped_cbs()
    for _ in range(8):  # drain to the small-scope bucket
        solver.step(resources, 0)
        t[0] += 1.0
    resources[0].store.assign("x", 60.0, 5.0, 0.0, 5.0, 1)
    solver.step(resources, 0)
    t[0] += 1.0
    assert scoped_cbs() == {8, 16}
    n_keys = len(solver._tick_fns)
    # Another small scope (different rows, same bucket): no recompile.
    resources[3].store.assign("x", 60.0, 5.0, 0.0, 6.0, 1)
    solver.step(resources, 0)
    t[0] += 1.0
    assert len(solver._tick_fns) == n_keys
    assert scoped_cbs() == {8, 16}


def test_wide_straddling_chunk_closure():
    """The group-closure invariant on the wide path: ONE dirty slot of
    a resource that straddles several chunk rows scopes the segment's
    ENTIRE row span (per-segment lanes couple every chunk), and only
    that segment."""
    from doorman_tpu.core.resource import Resource
    from doorman_tpu.proto import doorman_pb2 as pb

    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine = native.StoreEngine(clock=clock)
    resources = []
    for r, n_clients in ((0, 30), (1, 30), (2, 6)):
        # FAIR_SHARE: the level depends on wants/subclients only, so
        # the solve converges bitwise in one tick and the settled
        # frontier is exactly empty (see
        # test_quiet_ticks_shrink_scope_to_fixpoint for why a
        # has-coupled lane may keep a ULP-cycling floor).
        tpl = pb.ResourceTemplate(
            identifier_glob=f"wide{r}",
            capacity=500.0,
            algorithm=pb.Algorithm(
                kind=pb.Algorithm.FAIR_SHARE,
                lease_length=60, refresh_interval=5,
            ),
        )
        res = Resource(
            f"wide{r}", tpl, clock=clock, store_factory=engine.store
        )
        for c in range(n_clients):
            res.store.assign(f"w{r}_{c}", 60.0, 5.0, 0.0, 7.0 + c, 1)
        resources.append(res)
    solver = WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8, scoped=True,
    )
    solver.step(resources, 0)  # rebuild (full)
    t[0] += 1.0
    # Settle the post-rebuild frontier to empty.
    for _ in range(8):
        solver.step(resources, 0)
        t[0] += 1.0
    assert solver.last_scope == {"rows": 0, "resources": 0}
    # One slot of resource 0 (30 clients / width 8 -> 4 chunk rows).
    resources[0].store.assign("w0_3", 60.0, 5.0, 0.0, 99.0, 1)
    solver.step(resources, 0)
    assert solver.last_solve_mode == "scoped"
    assert solver.last_scope["resources"] == 1
    assert solver.last_scope["rows"] == 4  # the whole straddling span


def test_mesh_per_shard_scope_extents():
    """Mesh narrow ticks group the scope by owning shard: the handle's
    per-shard scoped counts sum to the global scope and the moved
    feedback still retires converged rows."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    solver = _make("resident_mesh", engine, clock, scoped=True)
    solver.step(resources, 0)
    t[0] += 1.0
    # Dirty rows on two different shards (rows split across devices).
    for i in (0, 11):
        resources[i].store.assign(
            f"c{i}_0", 60.0, 5.0,
            resources[i].store.get(f"c{i}_0").has, 51.0 + i, 1,
        )
    handle = solver.dispatch(resources, 0)
    assert handle.scope_ids is not None
    assert handle.scope_counts is not None
    assert int(handle.scope_counts.sum()) == len(handle.scope_ids)
    assert (np.diff(handle.scope_ids) > 0).all()  # sorted, unique
    solver.collect(handle)
    t[0] += 1.0
    # Quiet ticks drain the frontier through the per-shard moved mask.
    for _ in range(8):
        solver.step(resources, 0)
        t[0] += 1.0
    assert len(solver._scope) == 0


def test_scoped_toggle_mid_run_keeps_parity():
    """Flipping scoped_solve at runtime (triage flow) keeps stores
    byte-identical to an always-full reference; re-enabling re-seeds
    the frontier before the next scoped tick."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    ref = _make("resident", eng_a, clock, scoped=False)
    toggled = _make("resident", eng_b, clock, scoped=True)
    rng_a, rng_b = (np.random.default_rng(31) for _ in range(2))
    for step in range(8):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        if step == 3:
            toggled.scoped_solve = False
        if step == 5:
            toggled.scoped_solve = True
        ref.step(res_a, 0)
        toggled.step(res_b, 0)
        assert all_leases(res_a) == all_leases(res_b), f"step {step}"
        t[0] += 1.0
    assert toggled.solve_modes["scoped"] >= 2


def test_scope_status_block():
    """The /debug/status scope block reports plain host values (mode,
    reason, scope, frontier, tick split) — what the server's status()
    embeds per resident path."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    engine, resources = make_world(clock)
    solver = _make("resident", engine, clock, scoped=True)
    solver.step(resources, 0)
    st = solver.scope_status()
    assert st["enabled"] is True
    assert st["last_mode"] == "full"
    assert st["last_full_reason"] == "rebuild"
    assert st["full_ticks"] == 1 and st["scoped_ticks"] == 0
    t[0] += 1.0
    resources[0].store.assign("c0_0", 60.0, 5.0, 0.0, 9.0, 1)
    solver.step(resources, 0)
    st = solver.scope_status()
    assert st["last_mode"] == "scoped"
    assert st["last_full_reason"] is None
    assert st["scoped_ticks"] == 1
    assert st["last_scope_rows"] >= 1
    assert st["frontier"] >= 1
