"""Persistence subsystem: snapshot/journal round-trip, crash replay,
expiry-aware compaction, warm master takeover.

The acceptance contract: serialize -> restore reproduces the LeaseStore
state byte-identically (Python and native engines), a torn journal tail
(crash mid-flush) loses at most the final flush batch, compaction drops
only dead weight, and a fresh master restores + skips learning for
fresh state while any corruption degrades to the cold path."""

import asyncio
import json

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.core.lease import Lease
from doorman_tpu.core.store import LeaseStore
from doorman_tpu.persist import PersistManager
from doorman_tpu.persist import journal as journal_mod
from doorman_tpu.persist import snapshot as snapshot_mod
from doorman_tpu.persist.backend import (
    FileBackend,
    MemoryBackend,
    parse_backend,
)
from doorman_tpu.persist.restore import learning_end_for, restore_server
from doorman_tpu.persist.snapshot import SnapshotError
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 30,
              refresh_interval: 1, learning_mode_duration: 10}
"""


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# Backends
# ---------------------------------------------------------------------------


def test_file_backend_snapshot_atomic_and_journal(tmp_path):
    b = FileBackend(str(tmp_path / "persist"))
    assert b.read_snapshot() is None
    b.write_snapshot(b"snap-1")
    b.write_snapshot(b"snap-2")
    assert b.read_snapshot() == b"snap-2"

    assert b.read_journal() == []
    b.append_journal([b"one", b"two"])
    b.append_journal([b"three"])
    assert b.read_journal() == [b"one", b"two", b"three"]
    b.reset_journal([b"four"])
    assert b.read_journal() == [b"four"]
    b.reset_journal()
    assert b.read_journal() == []


def test_file_backend_surfaces_torn_tail(tmp_path):
    b = FileBackend(str(tmp_path))
    b.append_journal([b'[1,0,"d"]', b'[2,0,"d"]'])
    with open(b._journal_path, "ab") as f:
        f.write(b'[3,0,"d')  # crash mid-append: no newline, torn JSON
    lines = b.read_journal()
    assert lines[-1] == b'[3,0,"d'  # surfaced raw ...
    recs = journal_mod.read_records(lines)
    assert [r.seq for r in recs] == [1, 2]  # ... and dropped by the parser


def test_parse_backend_specs(tmp_path):
    assert isinstance(
        parse_backend(f"file:{tmp_path}/p"), FileBackend
    )
    with pytest.raises(ValueError):
        parse_backend("file")
    with pytest.raises(ValueError):
        parse_backend("s3:bucket")
    with pytest.raises(ValueError):
        parse_backend("etcd:/doorman/persist")  # no endpoints


# ---------------------------------------------------------------------------
# Snapshot framing
# ---------------------------------------------------------------------------


def _sample_snapshot():
    return snapshot_mod.MasterSnapshot(
        server_id="s0",
        taken_at=123.5,
        became_master_at=100.0,
        config_epoch=7,
        seq=42,
        resources=[
            snapshot_mod.ResourceSnapshot(
                id="r0",
                learning_mode_end=110.0,
                rows=[("c0", 150.0, 1.0, 10.0, 20.0, 1, 0),
                      ("c1", 151.0, 1.0, 30.0, 30.0, 2, 1)],
            )
        ],
        server_bands=[("r0", "child", [0, 1])],
    )


def test_snapshot_round_trip():
    snap = _sample_snapshot()
    data = snapshot_mod.encode(snap)
    again = snapshot_mod.decode(data)
    assert again == snap
    # Canonical: encoding the decoded snapshot is a fixpoint.
    assert snapshot_mod.encode(again) == data


def test_snapshot_rejects_corruption():
    data = snapshot_mod.encode(_sample_snapshot())
    flipped = data[:-5] + bytes([data[-5] ^ 0x01]) + data[-4:]
    with pytest.raises(SnapshotError):
        snapshot_mod.decode(flipped)
    with pytest.raises(SnapshotError):
        snapshot_mod.decode(data[: len(data) // 2])  # truncated payload
    header, _, body = data.partition(b"\n")
    env = json.loads(header)
    env["format"] = 99
    with pytest.raises(SnapshotError):
        snapshot_mod.decode(
            json.dumps(env).encode() + b"\n" + body
        )


# ---------------------------------------------------------------------------
# Journal: replay after a mid-interval crash, compaction
# ---------------------------------------------------------------------------


def _lease(expiry, has=5.0, wants=10.0):
    return Lease(expiry=expiry, refresh_interval=1.0, has=has,
                 wants=wants, subclients=1, priority=0)


def test_journal_replay_after_mid_interval_crash(tmp_path):
    b = FileBackend(str(tmp_path))
    j = journal_mod.Journal(b)
    j.record_assign(1.0, "r0", "c0", _lease(100.0))
    j.record_assign(2.0, "r0", "c1", _lease(101.0))
    j.flush()
    j.record_assign(3.0, "r0", "c2", _lease(102.0))
    # CRASH: the third record was never flushed. A new writer process
    # reads back only the flushed prefix.
    recs = journal_mod.read_records(b.read_journal())
    assert [(r.resource, r.client) for r in recs] == [
        ("r0", "c0"), ("r0", "c1")
    ]
    # Replayed leases carry their exact values.
    assert recs[0].lease == _lease(100.0)


def test_journal_sequence_regression_fences_stale_suffix():
    b = MemoryBackend()
    b.append_journal([b'[5,1.0,"d"]', b'[3,2.0,"d"]', b'[6,3.0,"d"]'])
    recs = journal_mod.read_records(b.read_journal())
    # Stop at the first regression — everything after is suspect.
    assert [r.seq for r in recs] == [5]


def test_journal_compaction_is_expiry_aware():
    b = MemoryBackend()
    j = journal_mod.Journal(b)
    j.record_assign(1.0, "r0", "dead", _lease(50.0))      # expires
    j.record_assign(2.0, "r0", "live", _lease(500.0, has=1.0))
    j.record_assign(3.0, "r0", "live", _lease(500.0, has=2.0))  # superseded
    j.record_assign(4.0, "r0", "gone", _lease(500.0))
    j.record_release(5.0, "r0", "gone")
    j.record_down(6.0)
    j.flush()
    before, after = j.compact(now=100.0)
    assert before == 6
    recs = journal_mod.read_records(b.read_journal())
    kinds = [(r.kind, r.client) for r in recs]
    # Kept: the live client's LAST assign, the release (the snapshot
    # underneath might still carry "gone"), the step-down marker.
    assert kinds == [
        ("a", "live"), ("r", "gone"), ("d", "")
    ]
    assert recs[0].lease.has == 2.0
    assert after == 3
    # Seqs survive compaction untouched (snapshot fencing still works).
    assert [r.seq for r in recs] == [3, 5, 6]


# ---------------------------------------------------------------------------
# Warm takeover end to end (server-level)
# ---------------------------------------------------------------------------


def _mk_server(backend, clock, *, server_id="s0", native=False,
               snapshot_interval=5.0):
    persist = PersistManager(
        backend, snapshot_interval=snapshot_interval,
        flush_interval=1.0, clock=clock,
    )
    return CapacityServer(
        server_id, TrivialElection(), mode="immediate",
        clock=clock, native_store=native, persist=persist,
    )


def _decide(server, resource, client, wants, has=0.0):
    from doorman_tpu.algorithms import Request

    lease, _ = server._decide(resource, Request(client, has, wants, 1))
    return lease


async def _configured(server):
    await server.load_config(parse_yaml_config(CONFIG))
    return server


def _store_rows(server, rid):
    return sorted(server.resources[rid].store.dump_rows())


@pytest.mark.parametrize("native", [False, True])
def test_snapshot_restore_round_trip_byte_identical(native):
    if native:
        from doorman_tpu import native as native_mod

        if not native_mod.native_available():
            pytest.skip("native store engine unavailable")

    async def run():
        clock = FakeClock()
        backend = MemoryBackend()
        s0 = await _configured(_mk_server(backend, clock, native=native))
        # Out of learning mode: decide real grants.
        s0.resources = {}
        s0.became_master_at = clock.t - 1000.0
        for i in range(5):
            _decide(s0, "r0", f"c{i}", wants=10.0 * (i + 1))
        clock.advance(1.0)
        s0.persist_step()  # flush + first snapshot
        want = _store_rows(s0, "r0")
        assert len(want) == 5

        clock.advance(1.0)
        s1 = await _configured(
            _mk_server(backend, clock, server_id="s1", native=native)
        )
        assert s1.last_restore is not None
        assert s1.last_restore["mode"] == "warm"
        assert s1.last_restore["leases_restored"] == 5
        # Byte-identical store state: every lease row round-trips,
        # including absolute expiry stamps.
        assert _store_rows(s1, "r0") == want
        await s0.stop()
        await s1.stop()

    asyncio.run(run())


def test_journal_covers_post_snapshot_deltas_and_releases():
    async def run():
        clock = FakeClock()
        backend = MemoryBackend()
        s0 = await _configured(_mk_server(backend, clock))
        s0.resources = {}
        s0.became_master_at = clock.t - 1000.0
        _decide(s0, "r0", "c0", wants=10.0)
        _decide(s0, "r0", "c1", wants=20.0)
        s0.persist_step()  # snapshot covers c0, c1
        # Post-snapshot deltas ride the journal only:
        _decide(s0, "r0", "c2", wants=30.0)
        _decide(s0, "r0", "c0", wants=15.0)  # demand change
        s0.resources["r0"].release("c1")
        s0._persist.record_release("r0", "c1")
        s0._persist.journal.flush()  # crash before the next snapshot
        want = _store_rows(s0, "r0")

        clock.advance(1.0)
        s1 = await _configured(_mk_server(backend, clock, server_id="s1"))
        assert s1.last_restore["mode"] == "warm"
        assert _store_rows(s1, "r0") == want
        assert not s1.resources["r0"].store.has_client("c1")
        await s0.stop()
        await s1.stop()

    asyncio.run(run())


def test_restore_drops_expired_and_clamps_overcommit():
    async def run():
        clock = FakeClock()
        backend = MemoryBackend()
        s0 = await _configured(_mk_server(backend, clock))
        s0.resources = {}
        s0.became_master_at = clock.t - 1000.0
        res = s0.resources  # noqa: F841
        r = s0.get_or_create_resource("r0")
        # Hand-build grants that will overcommit a capacity cut and one
        # lease that expires before the takeover.
        r.store.assign("big", 30.0, 1.0, 80.0, 80.0, 1)
        r.store.assign("small", 30.0, 1.0, 40.0, 40.0, 1)
        r.store.assign("lapsing", 2.0, 1.0, 10.0, 10.0, 1)
        for c in ("big", "small", "lapsing"):
            s0._persist.record_assign("r0", c, r.store.get(c))
        s0.persist_step()

        clock.advance(5.0)  # "lapsing" is now expired
        s1 = await _configured(_mk_server(backend, clock, server_id="s1"))
        info = s1.last_restore["resources"]["r0"]
        assert s1.last_restore["leases_dropped_expired"] == 1
        assert info["clamped"] is True
        store = s1.resources["r0"].store
        assert not store.has_client("lapsing")
        # Restored grants never exceed capacity (120 -> scaled to 100).
        assert store.sum_has == pytest.approx(100.0)
        assert store.get("big").has == pytest.approx(80.0 * 100.0 / 120.0)
        await s0.stop()
        await s1.stop()

    asyncio.run(run())


def test_corrupt_snapshot_falls_back_to_cold():
    async def run():
        clock = FakeClock()
        backend = MemoryBackend()
        s0 = await _configured(_mk_server(backend, clock))
        s0.resources = {}
        s0.became_master_at = clock.t - 1000.0
        _decide(s0, "r0", "c0", wants=10.0)
        s0.persist_step()
        backend._snapshot = b"garbage" + backend._snapshot[10:]

        clock.advance(1.0)
        s1 = await _configured(_mk_server(backend, clock, server_id="s1"))
        assert s1.last_restore["mode"] == "cold_error"
        assert s1.resources == {}  # exactly the reference's cold wipe
        await s0.stop()
        await s1.stop()

    asyncio.run(run())


def test_learning_mode_semantics():
    # Clean step-down: journal complete -> skip outright.
    end, kind = learning_end_for(
        age=500.0, clean_down=True, duration=10.0, became_master_at=1000.0
    )
    assert (end, kind) == (0.0, "skip")
    # Crash with fresh state -> shorten to exactly the staleness.
    end, kind = learning_end_for(
        age=3.0, clean_down=False, duration=10.0, became_master_at=1000.0
    )
    assert (end, kind) == (1003.0, "shorten")
    # Stale beyond the window -> the cold path.
    end, kind = learning_end_for(
        age=30.0, clean_down=False, duration=10.0, became_master_at=1000.0
    )
    assert (end, kind) == (1010.0, "cold")  # the full window, no more
    # No learning window configured: nothing to skip.
    assert learning_end_for(
        age=0.0, clean_down=False, duration=0.0, became_master_at=1000.0
    ) == (0.0, "skip")


def test_warm_takeover_skips_learning_after_clean_step_down():
    async def run():
        clock = FakeClock()
        backend = MemoryBackend()
        s0 = await _configured(_mk_server(backend, clock))
        s0.resources = {}
        s0.became_master_at = clock.t - 1000.0
        _decide(s0, "r0", "c0", wants=10.0)
        s0.persist_step()
        # Clean step-down writes the terminal marker.
        await s0._on_is_master(False)

        clock.advance(3.0)
        s1 = await _configured(_mk_server(backend, clock, server_id="s1"))
        assert s1.last_restore["clean_down"] is True
        info = s1.last_restore["resources"]["r0"]
        assert info["learning"] == "skip"
        assert not s1.resources["r0"].in_learning_mode
        await s0.stop()
        await s1.stop()

    asyncio.run(run())


def test_crash_takeover_shortens_learning():
    async def run():
        clock = FakeClock()
        backend = MemoryBackend()
        s0 = await _configured(_mk_server(backend, clock))
        s0.resources = {}
        s0.became_master_at = clock.t - 1000.0
        _decide(s0, "r0", "c0", wants=10.0)
        s0.persist_step()
        # NO step-down marker: s0 just dies.

        clock.advance(4.0)
        s1 = await _configured(_mk_server(backend, clock, server_id="s1"))
        info = s1.last_restore["resources"]["r0"]
        assert info["learning"] == "shorten"
        res = s1.resources["r0"]
        assert res.in_learning_mode
        # Learning covers exactly the 4s staleness, not the full 10s.
        assert res.learning_mode_end == pytest.approx(clock.t + 4.0)
        await s0.stop()
        await s1.stop()

    asyncio.run(run())


def test_server_bands_rebuilt_from_restore():
    async def run():
        from doorman_tpu.server.server import _band_key

        clock = FakeClock()
        backend = MemoryBackend()
        s0 = await _configured(_mk_server(backend, clock))
        s0.resources = {}
        s0.became_master_at = clock.t - 1000.0
        r = s0.get_or_create_resource("r0")
        bkey = _band_key("downstream", 1)
        r.store.assign(bkey, 30.0, 1.0, 5.0, 5.0, 3, priority=1)
        s0._persist.record_assign("r0", bkey, r.store.get(bkey))
        s0._server_bands[("r0", "downstream")] = {1}
        s0.persist_step()

        clock.advance(1.0)
        s1 = await _configured(_mk_server(backend, clock, server_id="s1"))
        assert s1._server_bands == {("r0", "downstream"): {1}}
        await s0.stop()
        await s1.stop()

    asyncio.run(run())


def test_persist_obs_spans_and_metrics():
    """Snapshot/restore land `persist.*` spans on the tracer and move
    the default-registry gauges/histograms."""
    from doorman_tpu.obs import metrics as metrics_mod
    from doorman_tpu.obs import trace as trace_mod

    async def run():
        tracer = trace_mod.default_tracer()
        tracer.enable(capacity=4096)
        tracer.clear()
        try:
            clock = FakeClock()
            backend = MemoryBackend()
            s0 = await _configured(_mk_server(backend, clock))
            s0.resources = {}
            s0.became_master_at = clock.t - 1000.0
            _decide(s0, "r0", "c0", wants=10.0)
            s0.persist_step()
            clock.advance(1.0)
            s1 = await _configured(
                _mk_server(backend, clock, server_id="s1")
            )
            names = {s.name for s in tracer.snapshot()}
            assert "persist.snapshot" in names
            assert "persist.restore" in names
            assert tracer.open_spans() == []

            reg = metrics_mod.default_registry()
            assert reg.gauge(
                "doorman_persist_snapshot_bytes", labels=("server",)
            ).value("s0") > 0
            assert reg.histogram(
                "doorman_persist_restore_seconds"
            ).count() >= 1
            assert reg.counter(
                "doorman_persist_restores_total",
                labels=("server", "mode"),
            ).value("s1", "warm") >= 1
            await s0.stop()
            await s1.stop()
        finally:
            tracer.disable()
            tracer.clear()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Etcd backend over the real HTTP dialect (fake etcd)
# ---------------------------------------------------------------------------


def test_etcd_backend_chunked_round_trip():
    from doorman_tpu.persist.backend import EtcdBackend
    from doorman_tpu.server.etcd import EtcdGateway
    from tests.fake_etcd import FakeEtcd

    fake = FakeEtcd()
    fake.start()
    try:
        gw = EtcdGateway([fake.address])
        b = EtcdBackend(gw, "/doorman/persist", chunk_bytes=8)
        assert b.read_snapshot() is None
        data = b"0123456789abcdefXYZ"  # 3 chunks at 8 bytes
        b.write_snapshot(data)
        assert b.read_snapshot() == data
        b.write_snapshot(b"gen2")  # supersede + prune gen 1
        assert b.read_snapshot() == b"gen2"
        assert gw.get_prefix("/doorman/persist/snap/00000001/") == []

        b.append_journal([b"r1", b"r2"])
        b.append_journal([b"r3"])
        assert b.read_journal() == [b"r1", b"r2", b"r3"]
        # A fresh backend instance recovers the append cursor.
        b2 = EtcdBackend(gw, "/doorman/persist", chunk_bytes=8)
        b2.append_journal([b"r4"])
        assert b2.read_journal() == [b"r1", b"r2", b"r3", b"r4"]
        b2.reset_journal([b"fresh"])
        assert b2.read_journal() == [b"fresh"]
    finally:
        fake.stop()


def test_etcd_gateway_prefix_helpers():
    from doorman_tpu.server.etcd import prefix_range_end

    assert prefix_range_end("/a/b/") == b"/a/b0"
    assert prefix_range_end(b"\xff") == b"\x00"
    assert prefix_range_end(b"a\xff") == b"b"
