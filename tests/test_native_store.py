"""The C++ store engine vs the Python LeaseStore: same interface, same
numbers, on identical operation sequences (differential testing); plus the
bulk pack path and the server wired with --native-store."""

import numpy as np
import pytest

from doorman_tpu import native
from doorman_tpu.core.store import LeaseStore

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native store build unavailable"
)


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture
def pair():
    clock = FakeClock()
    engine = native.StoreEngine(clock=clock)
    return LeaseStore("res", clock=clock), engine.store("res"), clock


def test_assign_release_sums_parity(pair):
    py, cc, clock = pair
    rng = np.random.default_rng(0)
    clients = [f"client-{i}" for i in range(40)]
    for step in range(500):
        c = clients[rng.integers(len(clients))]
        op = rng.random()
        if op < 0.6:
            wants = float(rng.integers(0, 100))
            has = float(rng.integers(0, 50))
            sub = int(rng.integers(1, 4))
            a = py.assign(c, 60.0, 5.0, has, wants, sub)
            b = cc.assign(c, 60.0, 5.0, has, wants, sub)
            assert a == b
        elif op < 0.8:
            py.release(c)
            cc.release(c)
        else:
            clock.t += float(rng.integers(0, 30))
            assert py.clean() == cc.clean()
        assert len(py) == len(cc)
        assert py.count == cc.count
        assert py.sum_has == pytest.approx(cc.sum_has)
        assert py.sum_wants == pytest.approx(cc.sum_wants)
        assert py.get(c) == cc.get(c)
        assert py.has_client(c) == cc.has_client(c)


def test_items_and_status_content_parity(pair):
    py, cc, clock = pair
    for i in range(10):
        py.assign(f"c{i}", 60.0, 5.0, float(i), float(2 * i), 1)
        cc.assign(f"c{i}", 60.0, 5.0, float(i), float(2 * i), 1)
    # Same content; order may differ after swap-removes, so compare as
    # dicts (both sides are deterministic, which test_pack_* checks).
    assert dict(py.items()) == dict(cc.items())
    a, b = py.lease_status(), cc.lease_status()
    assert (a.id, a.sum_has, a.sum_wants) == (b.id, b.sum_has, b.sum_wants)
    assert {s.client_id: s.lease for s in a.leases} == {
        s.client_id: s.lease for s in b.leases
    }


def test_subclients_and_zero_lease(pair):
    py, cc, _ = pair
    assert cc.get("ghost").is_zero
    assert cc.subclients("ghost") == 0
    cc.assign("c", 60.0, 5.0, 1.0, 2.0, 3)
    assert cc.subclients("c") == 3


def test_engine_pack_resource_major():
    clock = FakeClock()
    engine = native.StoreEngine(clock=clock)
    stores = [engine.store(f"res{i}") for i in range(3)]
    expect = []
    for r, s in enumerate(stores):
        for j in range(r + 1):  # 1, 2, 3 leases
            s.assign(f"c{r}-{j}", 60.0, 5.0, float(j), float(10 * r + j),
                     1 + j)
            expect.append((r, f"c{r}-{j}", float(10 * r + j), float(j),
                           float(1 + j)))
    assert engine.total_leases == 6
    ridx, cid, wants, has, sub, _prio = engine.pack(stores)
    got = [
        (int(ridx[i]), engine.client_name(int(cid[i])), wants[i], has[i],
         sub[i])
        for i in range(len(ridx))
    ]
    assert got == expect
    # Pack order follows the caller's order argument, not creation order:
    # reversed, res2's three leases come first as segment 0.
    ridx2, cid2, *_ = engine.pack(stores[::-1])
    assert [int(r) for r in ridx2] == [0, 0, 0, 1, 1, 2]
    assert engine.client_name(int(cid2[0])) == "c2-0"


def test_pack_after_release_swaps_deterministically():
    clock = FakeClock()
    engine = native.StoreEngine(clock=clock)
    s = engine.store("res")
    for i in range(4):
        s.assign(f"c{i}", 60.0, 5.0, 0.0, float(i), 1)
    s.release("c0")  # swap-remove: c3 moves into slot 0
    names = [c for c, _ in s.items()]
    assert names == ["c3", "c1", "c2"]


def test_clean_exact_boundary(pair):
    py, cc, clock = pair
    py.assign("c", 10.0, 5.0, 1.0, 1.0, 1)  # expiry 110
    cc.assign("c", 10.0, 5.0, 1.0, 1.0, 1)
    clock.t = 110.0  # now == expiry: NOT expired (strict >)
    assert py.clean() == cc.clean() == 0
    clock.t = 110.0001
    assert py.clean() == cc.clean() == 1


def _make_resources(store_factory, clock, n_resources=6, n_clients=15):
    from doorman_tpu.core.resource import Resource
    from doorman_tpu.proto import doorman_pb2 as pb

    rng = np.random.default_rng(11)
    kinds = [
        pb.Algorithm.PROPORTIONAL_SHARE,
        pb.Algorithm.FAIR_SHARE,
        pb.Algorithm.STATIC,
        pb.Algorithm.NO_ALGORITHM,
    ]
    resources = []
    for r in range(n_resources):
        t = pb.ResourceTemplate()
        t.identifier_glob = f"res{r}"
        t.capacity = float(rng.integers(50, 500))
        t.algorithm.kind = kinds[r % len(kinds)]
        t.algorithm.lease_length = 60
        t.algorithm.refresh_interval = 5
        res = Resource(
            f"res{r}", t, clock=clock, store_factory=store_factory
        )
        for c in range(int(rng.integers(1, n_clients))):
            res.store.assign(
                f"client-{c}", 60.0, 5.0,
                float(rng.integers(0, 50)), float(rng.integers(0, 100)), 1,
            )
        resources.append(res)
    return resources


def test_batch_tick_native_matches_python():
    """A full BatchSolver tick over native stores produces exactly the
    grants and store state of the Python-store tick (the native pack and
    dm_apply fast paths against the list-based reference path)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    from doorman_tpu.solver.batch import BatchSolver

    clock = FakeClock(500.0)
    py_res = _make_resources(None, clock)
    engine = native.StoreEngine(clock=clock)
    cc_res = _make_resources(engine.store, clock)

    solver_py = BatchSolver(clock=clock)
    solver_cc = BatchSolver(clock=clock)
    grants_py = solver_py.tick(py_res)
    grants_cc = solver_cc.tick(cc_res)
    assert grants_py == grants_cc
    for a, b in zip(py_res, cc_res):
        assert a.store.sum_has == pytest.approx(b.store.sum_has)
        assert a.store.sum_wants == pytest.approx(b.store.sum_wants)
        assert dict(a.store.items()) == dict(b.store.items())


def test_batch_apply_native_skips_released_and_vanished():
    import jax

    jax.config.update("jax_enable_x64", True)
    from doorman_tpu.solver.batch import BatchSolver

    clock = FakeClock(500.0)
    engine = native.StoreEngine(clock=clock)
    resources = _make_resources(engine.store, clock, n_resources=3)
    solver = BatchSolver(clock=clock)
    snap = solver.prepare(resources)
    gets = solver.solve(snap)
    # Mid-solve: one client releases, one resource vanishes.
    victim = next(iter(dict(resources[0].store.items())))
    resources[0].store.release(victim)
    dropped = resources.pop(1)
    grants = solver.apply(resources, snap, gets)
    assert victim not in grants.get("res0", {})
    assert dropped.id not in grants
    assert not dropped.store.has_client("client-0") or all(
        l.expiry <= 560.0 for _, l in dropped.store.items()
    )  # vanished resource got no fresh expiry stamps


def test_server_with_native_store():
    """The end-to-end server path on the native engine: grant, then a
    mastership reset wipes engine state."""
    import asyncio

    from doorman_tpu.proto import doorman_pb2 as pb
    from doorman_tpu.server import config as config_mod
    from doorman_tpu.server.election import TrivialElection
    from doorman_tpu.server.server import CapacityServer

    async def scenario():
        clock = FakeClock(1000.0)
        server = CapacityServer(
            "s1", TrivialElection(), minimum_refresh_interval=0.0,
            clock=clock, native_store=True,
        )
        assert server._store_factory is not None
        yaml_text = """
resources:
  - identifier_glob: "*"
    capacity: 100
    algorithm:
      kind: PROPORTIONAL_SHARE
      lease_length: 60
      refresh_interval: 5
"""
        await server.load_config(config_mod.parse_yaml_config(yaml_text))
        await server._on_is_master(True)
        server.became_master_at = clock() - 1000  # past learning mode

        req = pb.GetCapacityRequest()
        r = req.resource.add()
        r.resource_id = "res0"
        r.priority = 1
        r.wants = 50.0
        r.has.expiry_time = 0
        req.client_id = "client-a"
        resp = await server.GetCapacity(req, None)
        assert resp.response[0].gets.capacity == 50.0
        res = server.resources["res0"]
        assert type(res.store).__name__ == "NativeLeaseStore"
        assert res.store.sum_wants == 50.0

        # Mastership loss wipes the native engine state.
        await server._on_is_master(False)
        await server._on_is_master(True)
        assert server.resources == {}

    asyncio.run(scenario())


def test_band_aggregates_parity_and_bulk_refresh():
    """band_aggregates: same triples from the Python and native stores;
    bulk_refresh: wants update + stamp with has/priority preserved."""
    import numpy as np

    from doorman_tpu.core.store import LeaseStore

    engine = native.StoreEngine()
    ns = engine.store("r")
    ps = LeaseStore("r")
    for store in (ns, ps):
        store.assign("a", 60, 5, 3.0, 10.0, 1, priority=2)
        store.assign("b", 60, 5, 1.0, 5.0, 2, priority=1)
        store.assign("c", 60, 5, 0.0, 7.0, 1, priority=2)
    assert ns.band_aggregates() == ps.band_aggregates() == [
        (1, 5.0, 2), (2, 17.0, 2),
    ]

    rids = np.full(2, ns._rid, np.int32)
    cids = np.array(
        [engine.client_handle("a"), engine.client_handle("zz")], np.int64
    )
    n = engine.bulk_refresh(
        rids, cids, np.full(2, 1e12), np.full(2, 9.0), np.full(2, 42.0)
    )
    assert n == 1  # unknown client skipped
    lease = ns.get("a")
    assert lease.wants == 42.0 and lease.has == 3.0
    assert lease.priority == 2 and lease.refresh_interval == 9.0
    assert ns.sum_wants == 42.0 + 5.0 + 7.0


def test_drain_dirty2_classifies_wants_only_vs_full():
    """drain_dirty2 flags rows that changed beyond wants: membership,
    has, or subclients set dirty_full; pure wants churn (bulk_refresh,
    or assign with only wants moved) does not."""
    import numpy as np

    engine = native.StoreEngine()
    sa = engine.store("a")
    sb = engine.store("b")
    sc = engine.store("c")
    sa.assign("x", 60, 5, 0.0, 10.0, 1)
    sb.assign("y", 60, 5, 0.0, 10.0, 1)
    sc.assign("z", 60, 5, 0.0, 10.0, 1)
    rids, full = engine.drain_dirty2()
    assert set(rids) == {sa._rid, sb._rid, sc._rid}
    assert all(full)  # inserts are membership changes

    # wants-only churn: assign same has/sub, new wants -> not full.
    sa.assign("x", 60, 5, 0.0, 20.0, 1)
    # has change -> full (learning-mode echo must reach the device).
    sb.assign("y", 60, 5, 4.0, 10.0, 1)
    # bulk wants refresh -> not full.
    engine.bulk_refresh(
        np.asarray([sc._rid], np.int32),
        np.asarray([engine.client_handle("z")], np.int64),
        np.full(1, 1e12), np.full(1, 5.0), np.full(1, 30.0),
    )
    rids, full = engine.drain_dirty2()
    flags = dict(zip(rids.tolist(), full.tolist()))
    assert flags[sa._rid] == 0
    assert flags[sb._rid] == 1
    assert flags[sc._rid] == 0

    # release -> membership change -> full; subclient change -> full.
    sa.release("x")
    sb.assign("y", 60, 5, 4.0, 10.0, 3)
    rids, full = engine.drain_dirty2()
    flags = dict(zip(rids.tolist(), full.tolist()))
    assert flags[sa._rid] == 1 and flags[sb._rid] == 1

    # The flag is consumed by the drain: re-dirtying with wants only
    # afterwards reports not-full again.
    sb.assign("y", 60, 5, 4.0, 11.0, 3)
    rids, full = engine.drain_dirty2()
    assert dict(zip(rids.tolist(), full.tolist()))[sb._rid] == 0


def test_min_expiry_bound_sweeps_correctly():
    """The engine's per-resource min-expiry bound makes the per-tick
    sweep O(resources) in steady state; it must stay a valid LOWER
    bound through re-stamps that loosen it (later expiry on the same
    client) and recompute exactly whenever a scan happens."""
    t = [0.0]
    engine = native.StoreEngine(clock=lambda: t[0])
    store = engine.store("r")
    store.assign("a", 10.0, 5, 0.0, 1.0, 1)   # expires at 10
    store.assign("b", 30.0, 5, 0.0, 1.0, 1)   # expires at 30

    t[0] = 5.0
    assert engine.clean_all() == 0            # bound (10) skips the scan
    assert len(store) == 2

    # Re-stamp "a" far into the future: the bound stays loosely at 10.
    store.assign("a", 200.0, 5, 0.0, 1.0, 1)  # expires at 205

    t[0] = 50.0
    assert engine.clean_all() == 1            # scans: only "b" lapsed
    assert store.has_client("a") and not store.has_client("b")

    t[0] = 150.0
    assert engine.clean_all() == 0            # recomputed bound skips
    t[0] = 250.0
    assert engine.clean_all() == 1            # "a" finally lapses
    assert len(store) == 0


def test_regrant_updates_has_without_dirtying_or_restamping():
    """regrant is the single-lease delivery write-back: has and the
    running sum move; expiry/refresh/wants stay put; the row is NOT
    marked dirty (delivery is the solver's own output, and a dirty mark
    would force a device re-upload and defeat the idle fast path)."""
    engine = native.StoreEngine(clock=lambda: 100.0)
    store = engine.store("r")
    store.assign("a", 60.0, 5.0, 2.0, 10.0, 1)
    engine.drain_dirty2()  # consume the insert's dirty mark

    store.regrant("a", 7.5)
    lease = store.get("a")
    assert lease.has == 7.5 and store.sum_has == 7.5
    assert lease.expiry == 160.0 and lease.wants == 10.0
    rids, _ = engine.drain_dirty2()
    assert len(rids) == 0, "regrant dirtied the row"
    store.regrant("missing", 3.0)  # released mid-solve: no-op
    assert store.sum_has == 7.5


def test_out_of_range_resource_handles_are_noops():
    """Every extern entry point must treat an out-of-range resource
    handle as a no-op (skip / return 0 / zero-fill), never as an
    out-of-bounds read: the ctypes boundary should degrade a
    Python-level bookkeeping bug into a miss, not memory corruption.
    Exercised through raw lib calls with a handle the engine never
    issued."""
    import ctypes

    import numpy as np

    clock = FakeClock()
    engine = native.StoreEngine(clock=clock)
    store = engine.store("real")
    store.assign("c0", 60.0, 5.0, 1.0, 2.0, 1)
    lib, ptr = engine._lib, engine._ptr
    bad = 999  # never issued by dm_resource

    assert lib.dm_regrant(ptr, bad, 0, 5.0) == 0
    assert lib.dm_assign(ptr, bad, 0, 60.0, 5.0, 1.0, 2.0, 1, 0) == 0
    assert lib.dm_release(ptr, bad, 0) == 0
    assert lib.dm_clean(ptr, bad, ctypes.c_double(1e18)) == 0
    assert lib.dm_get(ptr, bad, 0, (ctypes.c_double * 6)()) == 0

    sums = (ctypes.c_double * 4)(7.0, 7.0, 7.0, 7.0)
    lib.dm_sums(ptr, bad, sums)
    assert list(sums) == [0.0, 0.0, 0.0, 0.0]

    out = (ctypes.c_int64 * 4)()
    assert lib.dm_dump(
        ptr, bad, out, (ctypes.c_double * 4)(), (ctypes.c_double * 4)(),
        (ctypes.c_double * 4)(), (ctypes.c_double * 4)(),
        (ctypes.c_int32 * 4)(), (ctypes.c_int64 * 4)(), 4
    ) == 0

    # dm_pack skips out-of-range order entries but keeps packing the
    # valid ones (segment ids still index the order array).
    order = np.array([bad, store._rid], np.int32)
    ridx = np.empty(4, np.int32)
    cid = np.empty(4, np.int64)
    w = np.empty(4, np.float64)
    h = np.empty(4, np.float64)
    s = np.empty(4, np.float64)
    p = np.empty(4, np.int64)
    n = lib.dm_pack(
        ptr, order.ctypes.data_as(native._I32P), 2,
        ridx.ctypes.data_as(native._I32P),
        cid.ctypes.data_as(native._I64P),
        w.ctypes.data_as(native._F64P), h.ctypes.data_as(native._F64P),
        s.ctypes.data_as(native._F64P), p.ctypes.data_as(native._I64P),
        4,
    )
    assert n == 1 and int(ridx[0]) == 1 and w[0] == 2.0

    # dm_apply: an edge whose segment maps to an out-of-range handle
    # is skipped (order[] upper bound), valid edges still apply.
    u8p = ctypes.POINTER(ctypes.c_uint8)
    ridx_a = np.array([0, 1], np.int32)
    cid_a = np.array([0, engine.client_handle("c0")], np.int64)
    gets = np.array([9.0, 3.5], np.float64)
    keep = np.zeros(2, np.uint8)
    applied_flags = np.zeros(2, np.uint8)
    applied = lib.dm_apply(
        ptr, order.ctypes.data_as(native._I32P), 2,
        ridx_a.ctypes.data_as(native._I32P),
        cid_a.ctypes.data_as(native._I64P),
        gets.ctypes.data_as(native._F64P), 2,
        keep.ctypes.data_as(u8p),
        applied_flags.ctypes.data_as(u8p),
    )
    assert applied == 1
    assert list(applied_flags) == [0, 1]
    assert store.get("c0").has == 3.5

    # And the real store's demand is untouched by all of the above.
    assert store.sum_wants == 2.0 and len(store) == 1
