"""Long chaos soaks (marked slow; tier-1 runs the smoke suite instead).

The shipped plans stretched to several fault/heal cycles and a longer
post-heal tail: every invariant must hold across repeated injections,
and determinism must survive the longer trajectory too."""

import asyncio
import dataclasses

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.chaos import ChaosRunner, get_plan
from doorman_tpu.chaos.plans import PLANS

pytestmark = pytest.mark.slow


def _stretched(name, cycles=3):
    """Repeat the plan's fault burst `cycles` times, spaced a full
    heal-plus-reconverge apart, with a long settled tail."""
    plan = get_plan(name)
    span = (plan.heal_tick - plan.warmup_ticks) + plan.reconverge_ticks + 4
    events = []
    for c in range(cycles):
        for ev in plan.events:
            events.append(dataclasses.replace(
                ev, at_tick=ev.at_tick + c * span
            ))
    last_heal = max(ev.at_tick + ev.duration_ticks for ev in events)
    return dataclasses.replace(
        plan,
        events=events,
        total_ticks=last_heal + plan.reconverge_ticks + 6,
    )


@pytest.mark.parametrize("name", sorted(PLANS))
def test_soak_repeated_fault_cycles(name):
    verdict = asyncio.run(ChaosRunner(_stretched(name)).run())
    assert verdict["violations"] == [], verdict["event_log"]
    assert verdict["ok"], verdict


def test_soak_determinism():
    plan = _stretched("master_flap")
    v1 = asyncio.run(ChaosRunner(plan).run())
    v2 = asyncio.run(ChaosRunner(plan).run())
    assert v1["log_sha256"] == v2["log_sha256"]
