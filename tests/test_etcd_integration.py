"""Etcd v3 integration: the shared gateway client, the config source,
and the election lock against an in-process fake speaking the exact
v3 HTTP/JSON surface (tests/fake_etcd.py).

Capability parity: reference election is an etcd TTL lock
(go/server/election/election.go:89-172) and config watches etcd
(go/configuration/configuration.go:56-105). Both subsystems here speak
one API generation (v3) through one client (server/etcd.py)."""

import asyncio

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.server import sources
from doorman_tpu.server.election import EtcdKV, KVElection
from doorman_tpu.server.etcd import EtcdGateway
from tests.fake_etcd import FakeEtcd


@pytest.fixture()
def fake():
    server = FakeEtcd()
    server.start()
    yield server
    server.stop()


def test_gateway_kv_lease_txn_surface(fake):
    gw = EtcdGateway([fake.address])
    assert gw.get("/k") is None
    gw.put("/k", "v1")
    assert gw.get("/k") == b"v1"

    # Transactional create: only succeeds while the key is absent.
    assert gw.put_if_absent("/lock", "a") is True
    assert gw.put_if_absent("/lock", "b") is False
    assert gw.get("/lock") == b"a"

    # Leases: a key bound to a lease dies with it.
    lease = gw.lease_grant(10.0)
    assert gw.put_if_absent("/lease-lock", "holder", lease) is True
    assert gw.lease_keepalive(lease) > 0
    gw.lease_revoke(lease)
    assert gw.get("/lease-lock") is None
    assert gw.lease_keepalive(lease) == 0


def test_config_source_initial_get_and_watch(fake):
    gw = EtcdGateway([fake.address])
    gw.put("/config", "capacity: 1")
    source = sources.etcd("/config", [fake.address])

    async def body():
        first = await asyncio.wait_for(source(), timeout=10)
        assert first == b"capacity: 1"
        # The next version arrives through the watch.
        waiter = asyncio.ensure_future(source())
        await asyncio.sleep(0.3)
        gw.put("/config", "capacity: 2")
        second = await asyncio.wait_for(waiter, timeout=15)
        assert second == b"capacity: 2"

    asyncio.run(body())


def test_parse_source_etcd_uses_v3_gateway(fake):
    EtcdGateway([fake.address]).put("/cfg", "x: 1")
    source = sources.parse_source("etcd:/cfg", etcd_endpoints=[fake.address])

    async def body():
        assert await asyncio.wait_for(source(), timeout=10) == b"x: 1"

    asyncio.run(body())


class Recorder:
    """Collects election callbacks with an event per transition."""

    def __init__(self):
        self.is_master = None
        self.master = ""
        self.flips = []
        self.event = asyncio.Event()

    async def on_is_master(self, value):
        self.is_master = value
        self.flips.append(value)
        self.event.set()

    async def on_current(self, value):
        self.master = value
        self.event.set()

    async def wait_for(self, predicate, timeout=12.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while not predicate(self):
            remaining = deadline - asyncio.get_event_loop().time()
            assert remaining > 0, "condition not reached in time"
            self.event.clear()
            try:
                await asyncio.wait_for(self.event.wait(), remaining)
            except asyncio.TimeoutError:
                pass


def test_election_failover_master_lapses_standby_wins(fake):
    """A wins the TTL lock; when its lease lapses (as if it stopped
    renewing), A observes the loss on its next renewal and the standby
    B acquires within a TTL (reference election.go:89-172)."""

    async def body():
        kv_a, kv_b = EtcdKV([fake.address]), EtcdKV([fake.address])
        el_a = KVElection(kv_a, "/doorman/master", ttl=0.9)
        el_b = KVElection(kv_b, "/doorman/master", ttl=0.9)
        rec_a, rec_b = Recorder(), Recorder()

        await el_a.run("server-a", rec_a.on_is_master, rec_a.on_current)
        await rec_a.wait_for(lambda r: r.is_master is True)
        await el_b.run("server-b", rec_b.on_is_master, rec_b.on_current)
        await rec_b.wait_for(lambda r: r.master == "server-a")
        assert rec_b.is_master is None  # B never won while A holds
        assert fake.value("/doorman/master") == "server-a"

        # Fault injection: A's lease lapses server-side. A observes the
        # loss at its next renewal; it is then retired (a deposed master
        # immediately re-campaigns — the reacquire test covers that — so
        # proving the STANDBY wins requires taking A out of the race).
        fake.expire_key_lease("/doorman/master")
        await rec_a.wait_for(lambda r: r.is_master is False)
        assert rec_a.flips[:2] == [True, False]
        await el_a.stop()
        await rec_b.wait_for(lambda r: r.is_master is True)
        await rec_b.wait_for(lambda r: r.master == "server-b")
        assert fake.value("/doorman/master") == "server-b"

        await el_b.stop()

    asyncio.run(body())


def test_master_steps_down_when_key_deleted_despite_live_lease(fake):
    """Split-brain guard: an operator force-deleting the lock key (the
    lease itself stays alive) must depose the incumbent at its next
    renewal — renewing on the lease alone would leave two masters once
    a standby recreates the key."""

    async def body():
        kv_a, kv_b = EtcdKV([fake.address]), EtcdKV([fake.address])
        el_a = KVElection(kv_a, "/lock", ttl=0.9)
        el_b = KVElection(kv_b, "/lock", ttl=0.9)
        rec_a, rec_b = Recorder(), Recorder()
        await el_a.run("a", rec_a.on_is_master, rec_a.on_current)
        await rec_a.wait_for(lambda r: r.is_master is True)
        await el_b.run("b", rec_b.on_is_master, rec_b.on_current)

        fake.drop_key("/lock")  # etcdctl del: lease survives, key gone
        await rec_a.wait_for(lambda r: r.is_master is False)
        await el_a.stop()  # out of the re-campaign race (see above)
        await rec_b.wait_for(lambda r: r.is_master is True)
        assert fake.value("/lock") == "b"
        await el_b.stop()

    asyncio.run(body())


def test_election_reacquire_after_standby_departs(fake):
    """A deposed master keeps campaigning and retakes the lock when the
    incumbent's lease lapses."""

    async def body():
        kv = EtcdKV([fake.address])
        el = KVElection(kv, "/lock", ttl=0.9)
        rec = Recorder()
        await el.run("a", rec.on_is_master, rec.on_current)
        await rec.wait_for(lambda r: r.is_master is True)
        fake.expire_key_lease("/lock")
        await rec.wait_for(lambda r: r.is_master is False)
        await rec.wait_for(lambda r: r.is_master is True)
        assert rec.flips == [True, False, True]
        await el.stop()

    asyncio.run(body())


def test_degenerate_watch_does_not_busy_loop():
    """An endpoint whose /v3/watch answers instantly with a non-stream
    body (error page, non-streaming proxy) reports success under the
    gateway's lenient watch contract; the election's wait_for_change
    must still pace its cycles instead of hammering etcd back-to-back."""
    import json as _json
    import time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    import threading

    class InstantHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.0"

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = _json.dumps({"error": "watch unsupported"}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), InstantHandler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    addr = f"127.0.0.1:{httpd.server_address[1]}"

    async def body():
        kv = EtcdKV([addr])
        t0 = time.monotonic()
        for _ in range(8):
            await kv.wait_for_change("/lock", 0.3)
        return time.monotonic() - t0

    elapsed = asyncio.run(body())
    httpd.shutdown()
    httpd.server_close()
    # 8 degenerate cycles: a busy loop would finish in ~milliseconds;
    # the pacing floor (0.05s, escalating to the poll interval after 5
    # consecutive instant returns) keeps the rate bounded.
    assert elapsed >= 0.5, f"watch cycles not paced: {elapsed:.3f}s"


def test_acquire_fails_over_past_partitioned_endpoint(fake, monkeypatch):
    """A partitioned endpoint (accepts TCP, never answers) listed FIRST
    must not eat the whole operation budget: the gateway splits the
    budget across endpoints, so the healthy fake still gets a real
    share and the acquire wins. Before the deadline-budgeted failover,
    the first endpoint burned the full per-request timeout while the
    operation deadline (the same value) expired — acquire could never
    succeed with any unreachable endpoint ahead of a healthy one."""
    import socket
    import time

    blackhole = socket.socket()
    blackhole.bind(("127.0.0.1", 0))
    blackhole.listen(1)  # handshake completes; nothing ever answers
    addr = f"127.0.0.1:{blackhole.getsockname()[1]}"
    monkeypatch.setattr(EtcdKV, "REQUEST_TIMEOUT", 1.0)

    async def body():
        kv = EtcdKV([addr, fake.address])
        t0 = time.monotonic()
        won = await kv.acquire("/lock", "me", ttl=10.0)
        elapsed = time.monotonic() - t0
        assert won, "healthy second endpoint never got a fair budget"
        # Budget is 3x REQUEST_TIMEOUT + slack; the win must land
        # inside it, not after stacked per-endpoint timeouts.
        assert elapsed < 4.5, f"acquire took {elapsed:.2f}s"
        assert await kv.refresh("/lock", "me", ttl=10.0)

    try:
        asyncio.run(body())
    finally:
        blackhole.close()
    assert fake.value("/lock") == "me"


def test_acquire_sequential_rpcs_fit_the_operation_budget(fake, monkeypatch):
    """acquire issues get + lease_grant + put_if_absent sequentially; a
    slow-but-healthy etcd whose per-request latency exceeds a third of
    REQUEST_TIMEOUT must still win within the operation budget (3x).
    Under the old single-REQUEST_TIMEOUT deadline this combination
    could never acquire mastership at all."""
    monkeypatch.setattr(EtcdKV, "REQUEST_TIMEOUT", 0.5)
    fake.latency = 0.25  # 3 RPCs x 0.25s = 0.75s > 0.5s

    async def body():
        kv = EtcdKV([fake.address])
        assert await kv.acquire("/lock", "slowpoke", ttl=10.0)

    asyncio.run(body())
    fake.latency = 0.0
    assert fake.value("/lock") == "slowpoke"


def test_stop_during_inflight_acquire_leaves_no_pinned_lock(fake, monkeypatch):
    """Cancelling a campaign mid-acquire (KVElection.stop during
    shutdown) must not leave the lock key pinned under the departed
    server's id: the executor thread may win the lock AFTER the task
    died, and only the abandoned/backstop-revoke machinery reclaims it
    before the full TTL."""
    import time

    monkeypatch.setattr(EtcdKV, "REQUEST_TIMEOUT", 1.0)
    fake.latency = 0.4  # keep the acquire in flight when we cancel

    async def body():
        kv = EtcdKV([fake.address])
        task = asyncio.ensure_future(kv.acquire("/lock", "ghost", ttl=10.0))
        await asyncio.sleep(0.6)  # thread is between grant and put
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    asyncio.run(body())
    fake.latency = 0.0
    # The thread's abandoned check (or the caller's backstop revoke)
    # must reclaim the lock well before the 10s TTL would.
    deadline = time.time() + 5.0
    while time.time() < deadline and fake.value("/lock") is not None:
        time.sleep(0.1)
    assert fake.value("/lock") is None, "lock pinned by a cancelled acquire"


def test_watch_walk_reaches_healthy_endpoint_between_dead_ones(fake):
    """The watch's endpoint walk must try each endpoint once per call:
    with [dead, healthy, dead], the two connection-refused fast-fails
    advance the walk and the healthy endpoint establishes the watch
    (regression: the walk index once read the mutating rotation state,
    repeating dead endpoints and never reaching the healthy one)."""
    import socket

    def dead_addr():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()  # nothing listens: connection refused, instantly
        return f"127.0.0.1:{port}"

    gw = EtcdGateway([dead_addr(), fake.address, dead_addr()])
    gw.put("/k", "v0")
    assert gw.wait_for_change("/k", timeout=2.0) is True
    # Subsequent calls start straight at the endpoint that worked.
    assert gw.endpoints[gw._watch_endpoint].endswith(fake.address)


def test_degenerate_empty_close_endpoint_is_not_sticky(fake):
    """An endpoint that answers /v3/watch with an instant empty 200
    close never produced a watch frame; it must not be pinned as the
    preferred watch endpoint (regression: a clean close BEFORE any
    frame counted as 'established', making such an endpoint permanently
    sticky and degrading the watch to a busy loop)."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class EmptyClose(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.0"

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()  # zero frames, instant close

        def log_message(self, *args):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), EmptyClose)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    degenerate = f"127.0.0.1:{httpd.server_address[1]}"

    gw = EtcdGateway([degenerate, fake.address])
    gw.put("/k", "v0")
    # The walk must advance past the frameless endpoint and establish
    # on the healthy fake (idle timeout counts as established).
    assert gw.wait_for_change("/k", timeout=1.5) is True
    assert gw.endpoints[gw._watch_endpoint].endswith(fake.address)
    # And it stays on the healthy endpoint on later calls.
    assert gw.wait_for_change("/k", timeout=1.0) is True
    assert gw.endpoints[gw._watch_endpoint].endswith(fake.address)
    httpd.shutdown()
    httpd.server_close()


def test_refresh_survives_one_transient_hiccup(fake):
    """A single transient failure mid-renewal (slow etcd round-trip, a
    starved executor thread) must NOT read as mastership loss — the
    refresh retries once within its split budget. Definite losses
    (lease gone) still step down without retrying."""

    async def body():
        kv = EtcdKV([fake.address])
        assert await kv.acquire("/lock", "me", 10.0)

        orig_get = kv._gw.get
        calls = {"n": 0}

        def flaky_get(key, timeout=30.0):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient blip")
            return orig_get(key, timeout=timeout)

        kv._gw.get = flaky_get
        assert await kv.refresh("/lock", "me", 10.0) is True
        assert calls["n"] == 2  # retried exactly once
        # Still master: a later clean refresh works too.
        assert await kv.refresh("/lock", "me", 10.0) is True

    asyncio.run(body())


def test_refresh_definite_loss_does_not_retry(fake):
    """Lease revoked out from under the holder: keepalive reports TTL 0
    and the refresh steps down on the FIRST attempt (a retry could only
    widen the window in which a standby and the deposed master both
    think they hold the lock)."""

    async def body():
        kv = EtcdKV([fake.address])
        assert await kv.acquire("/lock", "me", 10.0)
        lease_id = kv._leases["/lock"]
        fake.expire_lease(lease_id)

        keepalives = {"n": 0}
        orig_ka = kv._gw.lease_keepalive

        def counting_ka(lid, timeout=30.0):
            keepalives["n"] += 1
            return orig_ka(lid, timeout=timeout)

        kv._gw.lease_keepalive = counting_ka
        assert await kv.refresh("/lock", "me", 10.0) is False
        assert keepalives["n"] == 1  # no retry on a definite loss

    asyncio.run(body())
