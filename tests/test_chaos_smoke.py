"""Fast deterministic chaos smoke suite (tier-1).

Every shipped plan runs on CPU and must uphold the invariants:
Σgrants <= capacity each tick, at most one master, lag-but-never-lead
leases, and post-heal reconvergence within the plan's budget. Beyond
the verdict bit, each scenario's event log is asserted to show the
behavior the plan was designed to provoke — a plan whose faults never
bite would pass vacuously."""

import asyncio

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.chaos import ChaosRunner, get_plan
from doorman_tpu.chaos.plans import PLANS


def run_plan(name):
    return asyncio.run(ChaosRunner(get_plan(name)).run())


@pytest.fixture(scope="module")
def verdicts():
    return {name: run_plan(name) for name in PLANS}


@pytest.mark.parametrize("name", sorted(PLANS))
def test_plan_upholds_invariants(verdicts, name):
    v = verdicts[name]
    assert v["violations"] == [], v["event_log"]
    assert v["ok"], v
    assert v["converged_after_heal_ticks"] is not None
    assert (
        v["converged_after_heal_ticks"]
        <= get_plan(name).reconverge_ticks
    )


def _kinds(v):
    return [e[1] for e in v["event_log"]]


def _masters_timeline(v):
    return [(e[0], e[2]) for e in v["event_log"] if e[1] == "master"]


def test_master_flap_fails_over_without_split_brain(verdicts):
    v = verdicts["master_flap"]
    timeline = _masters_timeline(v)
    # s0 wins, steps down during the brownout (a masterless gap is
    # expected — never two masters), s1 takes over.
    assert timeline[0][1] == ["s0"]
    assert [] in [m for _, m in timeline]
    assert ["s1"] in [m for _, m in timeline]


def test_master_flap_streaming_leg(verdicts):
    """The streaming subscriber rides the flap: establish + snapshot
    push at t0, SILENT at steady state (the RPC win — no poll events
    while the stream is healthy), terminal mastership redirect at the
    flip, poll fallback while masterless, re-establishment once a
    master answers — with every lease-window invariant intact (the
    plan-level ok covers the stream client too)."""
    v = verdicts["master_flap"]
    flap_tick = next(
        e[0] for e in v["event_log"] if e[1] == "fault"
    )
    streams = [e for e in v["event_log"] if e[1] == "stream"]
    assert streams, "no streaming leg in master_flap"
    by_tick = {e[0]: e[3] for e in streams}
    # Establishment with the snapshot push, before the fault.
    assert by_tick[0] == "establish" and streams[0][4] == 1
    # Healthy steady state is SILENT: no poll events before the flap
    # after establishment (pure pushes at most).
    for e in streams:
        if 0 < e[0] < flap_tick:
            assert "poll" not in e[3], f"steady-state poll at {e}"
    # The flip terminates the stream with a mastership redirect and
    # the client degrades to polling.
    assert any(
        "redirect" in ev and "poll" in ev
        for t, ev in by_tick.items() if t >= flap_tick
    ), "no redirect+poll fallback at the flip"
    # And a later clean re-establishment (snapshot push again).
    assert any(
        e[3] == "establish" and e[0] > flap_tick and e[4] >= 1
        for e in streams
    ), "stream never re-established after the flap"


def test_master_flap_warm_restores_instead_of_relearning(verdicts):
    v = verdicts["master_flap_warm"]
    plan = get_plan("master_flap_warm")
    restores = [e for e in v["event_log"] if e[1] == "restore"]
    # The initial election finds an empty backend (cold), the takeover
    # finds the predecessor's state (warm).
    assert [e[3] for e in restores] == ["cold_empty", "warm"]
    warm = restores[-1]
    server, mode, leases, clean_down, learning = warm[2:]
    assert server == "s1" and leases == len(plan.setup["wants"])
    # s0 stepped down cleanly, so the journal is complete and learning
    # is skipped outright for the restored resource...
    assert clean_down is True
    assert learning == [["r0", "skip"]]
    # ...which is what makes the 2-tick reconvergence budget meetable:
    # the cold path would spend learning_mode_duration (10 ticks)
    # serving conservative grants first.
    assert plan.reconverge_ticks < plan.setup["learning_mode_duration"]
    assert (
        v["converged_after_heal_ticks"] <= plan.reconverge_ticks
    )
    # The takeover happened during the fault window, not after heal:
    # restore, not relearn, is what closed the gap.
    assert warm[0] < v["heal_tick"]


WARM_VARIANTS = sorted(n for n in PLANS if n.startswith("master_flap_warm_"))


@pytest.mark.parametrize("name", WARM_VARIANTS)
def test_master_flap_warm_arc_per_fairness_lane(verdicts, name):
    """The warm-takeover contract is algorithm-independent: every
    fairness-portfolio lane (fair/maxmin/balanced/logutil) restores
    instead of relearning, skips learning on the clean step-down, and
    reconverges inside the SAME budget the proportional plan meets."""
    v = verdicts[name]
    plan = get_plan(name)
    restores = [e for e in v["event_log"] if e[1] == "restore"]
    assert [e[3] for e in restores] == ["cold_empty", "warm"]
    warm = restores[-1]
    server, _mode, leases, clean_down, learning = warm[2:]
    assert server == "s1" and leases == len(plan.setup["wants"])
    assert clean_down is True
    assert learning == [["r0", "skip"]]
    assert v["converged_after_heal_ticks"] <= plan.reconverge_ticks
    assert warm[0] < v["heal_tick"]


def test_master_flap_warm_variant_logs_deterministic(verdicts):
    """One representative portfolio parametrization replayed from
    scratch produces the module fixture's event log byte-for-byte —
    the per-kind determinism pin (the seeded-replay contract extends
    to the new lanes' solve paths)."""
    name = "master_flap_warm_maxmin"
    again = run_plan(name)
    assert again["event_log"] == verdicts[name]["event_log"]
    assert again["converged_after_heal_ticks"] == (
        verdicts[name]["converged_after_heal_ticks"]
    )


def test_client_storm_sheds_bottom_up_with_top_band_floor(verdicts):
    v = verdicts["client_storm"]
    plan = get_plan("client_storm")
    storm_tick = plan.events[0].at_tick
    tallies = v["admission"]["s0"]
    # The goodput floor: the top band is NEVER shed; the swarm's band
    # eats nearly all of the shedding; the middle band sees some (the
    # level collapse walks up from the bottom) but keeps its leases.
    assert tallies["GetCapacity/2"]["shed"] == 0
    assert tallies["GetCapacity/0"]["shed"] > tallies["GetCapacity/1"]["shed"] > 0
    # The shed matrix is law: every release is admitted — including
    # the swarm's own 20 releases when it drains at heal.
    assert tallies["ReleaseCapacity/0"]["shed"] == 0
    assert tallies["ReleaseCapacity/0"]["admitted"] >= 20
    storm = [e for e in v["event_log"] if e[1] == "storm"]
    assert len(storm) == plan.events[0].duration_ticks
    # The hard per-window cap bites in the storm's FIRST window — some
    # of the swarm is admitted under the budget (no blanket denial),
    # the rest sheds before the AIMD level ever moved...
    assert 0 < storm[0][2] < plan.events[0].params["clients"]
    # ...and once the level collapses the swarm is fully shed.
    assert storm[-1][2] == 0
    adm = [e for e in v["event_log"] if e[1] == "admission"]
    # Nothing shed before the storm, and the post-heal additive
    # recovery readmits every band before the run ends.
    assert all(e[4] == 0 for e in adm if e[0] < storm_tick)
    assert all(e[4] == 0 for e in adm[-3:])
    # The baseline clients ride through byte-unchanged: shed refreshes
    # retain leases, so convergence is immediate at heal.
    assert v["converged_after_heal_ticks"] == 0


def test_etcd_brownout_survives_single_hiccup_then_relearns(verdicts):
    v = verdicts["etcd_brownout"]
    plan = get_plan("etcd_brownout")
    hiccup_tick = plan.events[0].at_tick
    brownout_tick = plan.events[2].at_tick
    changes = _masters_timeline(v)
    # The single dropped renewal round-trip is retried, not a loss:
    assert all(t != hiccup_tick for t, _ in changes[1:])
    # The sustained brownout IS a loss, at exactly its start tick.
    assert (brownout_tick, []) in changes
    # ... and the same server re-wins after the heal.
    assert changes[-1][1] == ["s0"]


def test_device_tunnel_outage_degrades_to_tick_errors(verdicts):
    v = verdicts["device_tunnel_outage"]
    errors = [e for e in v["event_log"] if e[1] == "tick_error"]
    # The dead backend surfaces as per-tick errors, never as a
    # violation or a crash; serving continued from the stores.
    assert len(errors) == 3
    assert all("chaos: device backend unreachable" in e[3] for e in errors)


def test_intermediate_partition_degrades_then_heals(verdicts):
    v = verdicts["intermediate_partition"]
    kinds = _kinds(v)
    # The parent-lease expiry visibly degraded the clients (capacity
    # decays toward zero — no overcommit), then healed to baseline.
    assert "degraded" in kinds and "converged" in kinds
    degraded_tick = next(e[0] for e in v["event_log"] if e[1] == "degraded")
    assert degraded_tick < v["heal_tick"]


@pytest.mark.parametrize("name", sorted(PLANS))
def test_verdicts_carry_slo_and_flightrec(verdicts, name):
    """Every plan's verdict is an SLO surface and a black box: a
    reconvergence verdict always, per-band tallies on admission plans,
    and no flight-recorder dump on a clean run (violations are what
    trigger the dump — tests/test_flightrec.py forces one)."""
    v = verdicts[name]
    slo_v = {x["slo"]: x for x in v["slo"]["verdicts"]}
    recon = slo_v[f"{name}:reconverge_ticks"]
    assert recon["status"] == "pass"
    assert recon["observed"] == v["converged_after_heal_ticks"]
    assert recon["target"] == get_plan(name).reconverge_ticks
    # The deltas field is always present (None until a prior round
    # embedded the same verdict) — the trajectory contract.
    assert all("delta_vs_prev" in x for x in v["slo"]["verdicts"])
    assert v["slo"]["ok"]
    assert v["flightrec_dump"] is None


def test_grant_corruption_caught_by_shadow_audit(verdicts):
    """The shadow-oracle acceptance arc: a silently scaled grant that
    no structural invariant can see (it SHRINKS a row — capacity
    conservation, lag-never-lead, and band floors all still hold) is
    confirmed by the fixpoint audit within 2K ticks of the fault, with
    a deterministic verdict."""
    v = verdicts["grant_corruption"]
    plan = get_plan("grant_corruption")
    fault_tick = plan.events[0].at_tick
    sample_k = plan.setup["audit_sample"]
    # Invariants held — the corruption is invisible to them...
    assert v["violations"] == [] and v["ok"]
    # ...but the audit confirmed exactly one divergent state.
    audit = v["audit"]["s0"]
    assert audit["divergences"] == 1
    detail = audit["details"][0]
    assert detail["rid"] == "r0" and detail["clients"] == ["c0"]
    # The corrupted grant is the oracle's answer scaled by the fault's
    # factor — the audit caught the exact corruption, not noise.
    factor = plan.events[0].params["factor"]
    assert detail["has"][0] == pytest.approx(
        detail["expected"][0] * factor
    )
    # Detection latency: strike one at the first sample with stable
    # corrupted inputs, confirmation one sample later — within 2K
    # ticks of the fault, and the event log pins the exact tick.
    entries = [e for e in v["event_log"] if e[1] == "audit_divergence"]
    assert entries == [[detail["tick"], "audit_divergence", "s0", 1]]
    assert fault_tick < detail["tick"] <= fault_tick + 2 * sample_k
    # The anomaly detector's floor watch flags every post-confirmation
    # record (the standing-alarm property: a bit-identity violation
    # never reads as healthy again).
    det = v["detect"]
    assert det["per_field"]["audit_divergence"] > 0
    assert all(
        d["field"] == "audit_divergence" and d["value"] >= 1.0
        for d in det["detections"]
    )


def test_grant_corruption_verdict_is_byte_stable(verdicts):
    """Replaying the plan reproduces the audit verdict byte-for-byte:
    the inline comparator runs on virtual time, so divergence ticks,
    digests, and the detector's windowed output are all part of the
    seeded-replay contract."""
    again = run_plan("grant_corruption")
    v = verdicts["grant_corruption"]
    assert again["event_log"] == v["event_log"]
    assert again["log_sha256"] == v["log_sha256"]
    assert again["audit"] == v["audit"]
    assert again["detect"] == v["detect"]


def test_clean_plan_pins_audit_silence(verdicts):
    """The other half of the audit acceptance: a fault plan that never
    corrupts grants (device_tunnel_outage runs the same auditor at the
    same cadence) reports zero divergences and zero anomalies — the
    auditor does not cry wolf through solver outages, slow solves, or
    resident overflows."""
    v = verdicts["device_tunnel_outage"]
    audit = v["audit"]["s0"]
    assert audit["divergences"] == 0 and audit["details"] == []
    assert audit["samples"] > 0  # it actually ran
    assert v["detect"] is not None
    assert v["detect"]["anomalies"] == 0
    # Plans without an armed auditor carry an explicit None, never a
    # fabricated block.
    assert verdicts["master_flap"]["audit"] is None


def test_client_storm_slo_embeds_per_band_tallies(verdicts):
    """The acceptance surface: chaos client_storm emits a machine-
    readable top-band goodput verdict whose detail carries the exact
    per-band admitted/shed tallies."""
    v = verdicts["client_storm"]
    slo_v = {x["slo"]: x for x in v["slo"]["verdicts"]}
    floor = slo_v["client_storm:top_band_goodput"]
    assert floor["status"] == "pass" and floor["observed"] == 1.0
    per_band = floor["detail"]["per_band"]
    # Bottom band shed hardest, the top band never (mirrors the
    # top_band_floor invariant, now with a numeric trajectory).
    assert per_band["0"]["shed"] > 0
    assert per_band[str(floor["detail"]["band"])]["shed"] == 0
    # The verdict tallies agree with the runner's admission block.
    adm = v["admission"]["s0"]
    for band, counts in per_band.items():
        key = f"GetCapacity/{band}"
        assert adm[key]["admitted"] == counts["admitted"]
        assert adm[key]["shed"] == counts["shed"]


def test_frontend_worker_crash_resets_to_redirect_and_reestablishes(verdicts):
    """The serving-plane crash arc: the dead worker's streams end with
    a mastership redirect THE SAME TICK (never a silent lapse), their
    clients re-establish on the survivor the next tick, the streams the
    survivor held never notice, and the worker restarts at heal — with
    the full population re-homed and held by the end of the run."""
    v = verdicts["frontend_worker_crash"]
    crash = next(e for e in v["event_log"] if e[1] == "worker_crash")
    tick, _, server, worker, dropped = crash
    assert server == "s0" and worker == 0
    assert dropped > 0, "the crash must actually drop streams"
    # Every dropped stream's client saw the redirect the crash tick...
    redirects = [
        e for e in v["event_log"]
        if e[1] == "stream" and e[0] == tick and "redirect" in e[3]
    ]
    assert len(redirects) == dropped
    # ...and re-established the very next tick (onto the survivor —
    # pushes resume before the dead worker returns).
    reestablished = {
        e[2] for e in v["event_log"]
        if e[1] == "stream" and tick < e[0] < v["heal_tick"]
        and "establish" in e[3]
    }
    assert reestablished == {e[2] for e in redirects}
    restore = next(e for e in v["event_log"] if e[1] == "worker_restore")
    assert restore[0] == v["heal_tick"] and restore[3] == worker
    fe = v["frontend"]["s0"]
    assert fe["crashes"] == 1 and fe["restores"] == 1
    # Everyone is held again at the end; both workers live.
    assert fe["held"] == get_plan("frontend_worker_crash").setup["streams"]
    assert fe["live"] == [0, 1]
    # The restarted worker's reader resumed at the ring head: no frame
    # replay (a fresh cursor reports zero laps and no backlog debt).
    w0 = fe["per_worker"][0]
    assert w0["reader"]["laps"] == 0


def test_frontend_ring_stall_laps_loudly_on_resume(verdicts):
    """The serving-plane stall arc: a frozen pump over a tiny ring is
    LAPPED by the tick edge (appends never block); the resume pump
    reports the lap and resets every held stream to a redirect — the
    loud failure mode — after which clients re-establish and the pool
    returns to steady state."""
    v = verdicts["frontend_ring_stall"]
    stall = next(e for e in v["event_log"] if e[1] == "ring_stall")
    resume = next(e for e in v["event_log"] if e[1] == "ring_resume")
    assert stall[0] == get_plan("frontend_ring_stall").events[0].at_tick
    assert resume[0] == v["heal_tick"]
    # The resume pump surfaced the lap...
    pump = next(
        e for e in v["event_log"]
        if e[1] == "frontend_pump" and e[0] == resume[0]
    )
    assert pump[4] >= 1  # lapped
    # ...which reset the stalled worker's streams to redirects that
    # tick; the survivor's streams saw no redirect the whole run.
    redirected = {
        e[2] for e in v["event_log"]
        if e[1] == "stream" and e[0] == resume[0] and "redirect" in e[3]
    }
    assert redirected
    all_redirected = {
        e[2] for e in v["event_log"]
        if e[1] == "stream" and "redirect" in e[3]
    }
    assert all_redirected == redirected, (
        "streams outside the stalled worker were reset"
    )
    # Steady state after re-establishment: no redirects in the final
    # quarter of the run (the oscillation guard — the ring must hold a
    # healthy tick's traffic).
    last_q = v["ticks"] - (v["ticks"] - v["heal_tick"]) // 2
    assert not [
        e for e in v["event_log"]
        if e[1] == "stream" and e[0] >= last_q and "redirect" in e[3]
    ]
    fe = v["frontend"]["s0"]
    assert fe["held"] == get_plan("frontend_ring_stall").setup["streams"]
    assert fe["stalled"] == []


def test_frontend_crash_log_is_deterministic(verdicts):
    """The serving-plane arcs replay byte-identically: rings, pumps,
    crash/restore, redirects and re-establishments are all driven on
    the virtual clock."""
    again = run_plan("frontend_worker_crash")
    assert again["log_sha256"] == verdicts["frontend_worker_crash"]["log_sha256"]
    assert again["frontend"] == verdicts["frontend_worker_crash"]["frontend"]
