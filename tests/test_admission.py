"""Admission front-end: coalescing parity, AIMD shedding, the
retry-after contract, deadline fast-fail, and the debug surfaces.

The load-bearing test is the coalescing parity pin: the same request
stream through the per-request handler path and through the coalesced
grouped pass must yield byte-identical responses AND stores (Python and
native engines, mixed priority bands, `has`-carrying refreshes) — the
micro-batching front-end is an optimization, never a semantic change.
"""

import asyncio
import random

import grpc
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.admission import Admission, RETRY_AFTER_KEY
from doorman_tpu.admission.controller import AimdController
from doorman_tpu.admission.deadline import DecisionLatency, fast_fail_reason
from doorman_tpu.admission.policy import SHED_MATRIX, sheddable
from doorman_tpu.client import Client
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import CapacityStub
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer
from doorman_tpu.utils.backoff import backoff

CONFIG = """
resources:
- identifier_glob: prop
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 120
  safe_capacity: 3
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""


def run(coro):
    return asyncio.run(coro)


async def make_server(admission=None, clock=None, **kwargs):
    server = CapacityServer(
        "adm-test", TrivialElection(), mode="immediate",
        minimum_refresh_interval=0.0, admission=admission,
        **({"clock": clock} if clock is not None else {}), **kwargs,
    )
    port = await server.start(0, host="127.0.0.1")
    await server.load_config(parse_yaml_config(CONFIG))
    await asyncio.sleep(0)
    server.current_master = f"127.0.0.1:{port}"
    return server, f"127.0.0.1:{port}"


# ----------------------------------------------------------------------
# Controller
# ----------------------------------------------------------------------


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_aimd_level_collapses_under_overload_and_recovers():
    clock = FakeClock()
    ctl = AimdController(
        window=1.0, clock=clock, rng=random.Random(0), max_rps=10.0
    )
    # Calm traffic: level stays at 1, everything admitted.
    for tick in range(3):
        clock.t = float(tick)
        for _ in range(3):
            admitted, _ = ctl.admit(0)
            assert admitted
    assert ctl.level == 1.0
    # Storm: 30 arrivals/window for 4 windows; multiplicative decrease
    # every boundary.
    levels = []
    for tick in range(3, 7):
        clock.t = float(tick)
        for _ in range(30):
            ctl.admit(0)
        levels.append(ctl.level)
    assert levels[-1] < levels[0] <= 1.0
    assert ctl.overloaded_windows >= 3
    # Recovery: additive increase back to 1 once the storm stops.
    for tick in range(7, 25):
        clock.t = float(tick)
        ctl.admit(0)
    assert ctl.level == 1.0


def test_hard_cap_sheds_inside_the_spiking_window():
    ctl = AimdController(
        window=1.0, clock=FakeClock(), rng=random.Random(0), max_rps=5.0
    )
    outcomes = [ctl.admit(0)[0] for _ in range(12)]
    # The first window's budget (5) is admitted, the spike past it is
    # shed before any AIMD boundary — single-band, so no floor applies.
    assert outcomes[:5] == [True] * 5
    assert not any(outcomes[5:])


def test_bands_shed_bottom_up_and_top_band_never():
    clock = FakeClock()
    ctl = AimdController(
        window=1.0, clock=clock, rng=random.Random(1), max_rps=1000.0
    )
    for band in (0, 1, 2):
        ctl.admit(band)
    for level, expect_full, expect_zero in (
        (1.0, {0, 1, 2}, set()),
        (0.8, {1, 2}, set()),        # band 0 partially shed
        (2 / 3, {1, 2}, {0}),        # band 0 extinguished exactly here
        (0.5, {2}, {0}),             # band 1 partially shed
        (1 / 3, {2}, {0, 1}),        # band 1 extinguished
        (0.2, set(), {0, 1}),        # top band probability dips too —
                                     # but admit() floors it (below)
    ):
        ctl.level = level
        for band in (0, 1, 2):
            p = ctl.band_probability(band)
            if band in expect_zero:
                assert p == 0.0, (level, band, p)
            if band in expect_full:
                assert p == pytest.approx(1.0), (level, band, p)
        # Band probabilities are monotone in the band.
        assert (
            ctl.band_probability(0)
            <= ctl.band_probability(1)
            <= ctl.band_probability(2)
        )
    # The top band is admitted even at the floor level (lower bands
    # exist to shed first) — and the probability mapping never sheds it
    # anyway while level >= 1/B.
    ctl.level = ctl.min_level
    admitted, _ = ctl.admit(2)
    assert admitted


def test_retry_after_bounded_and_longer_for_deeper_bands():
    ctl = AimdController(window=1.0, clock=FakeClock(), rng=random.Random(2))
    for band in (0, 1, 2):
        ctl.admit(band)
    ctl.level = 0.1
    low, mid, top = (ctl.retry_after(b) for b in (0, 1, 2))
    assert low > mid > top >= ctl.window
    assert low <= ctl.max_retry_after


def test_backoff_full_jitter_opt_in():
    # Deterministic ladder unchanged by default.
    assert backoff(1.0, 60.0, 3) == pytest.approx(1.3**3)
    rng = random.Random(42)
    draws = [backoff(1.0, 60.0, 8, jitter=rng) for _ in range(64)]
    ladder = backoff(1.0, 60.0, 8)
    assert all(0.0 <= d <= ladder for d in draws)
    # Actually jittered (not the ladder value), and seeded-reproducible.
    assert len(set(round(d, 9) for d in draws)) > 32
    assert draws == [
        backoff(1.0, 60.0, 8, jitter=random.Random(42)) for _ in range(64)
    ][:64] or draws[0] == backoff(1.0, 60.0, 8, jitter=random.Random(42))


def test_shed_matrix():
    assert sheddable("GetCapacity")
    # Stream establishment is sheddable (a refused subscriber keeps
    # polling); the three never-shed rows stay never-shed.
    assert sheddable("WatchCapacity")
    for method in ("ReleaseCapacity", "GetServerCapacity", "Discovery"):
        assert not sheddable(method)
    assert set(SHED_MATRIX) == {
        "GetCapacity", "WatchCapacity", "GetServerCapacity",
        "ReleaseCapacity", "Discovery",
    }


def test_deadline_fast_fail_math():
    lat = DecisionLatency()
    lat.observe(0.02)

    class Ctx:
        def __init__(self, remaining):
            self._r = remaining

        def time_remaining(self):
            return self._r

    assert fast_fail_reason(None, 0.1, lat) is None
    assert fast_fail_reason(Ctx(None), 0.1, lat) is None
    assert fast_fail_reason(Ctx(10.0), 0.1, lat) is None
    reason = fast_fail_reason(Ctx(0.05), 0.1, lat)
    assert reason is not None and "fast-fail" in reason


# ----------------------------------------------------------------------
# Coalescing parity
# ----------------------------------------------------------------------


def _round_requests(round_index, prev=None):
    """A mixed stream: six clients over three bands, two resources,
    some requests carrying both resources; round 2 carries `has` from
    round 1's responses (a refreshing population)."""
    reqs = []
    for i in range(6):
        cid = f"cl{i}"
        req = pb.GetCapacityRequest(client_id=cid)
        rids = ["prop"] if i % 3 == 0 else ["fair"]
        if i % 2 == 0:
            rids = rids + (["fair"] if rids == ["prop"] else ["prop"])
        for rid in rids:
            rr = req.resource.add()
            rr.resource_id = rid
            rr.wants = 10.0 * (i + 1) + round_index
            rr.priority = i % 3
            if prev is not None:
                for resp in prev[cid].response:
                    if resp.resource_id == rid:
                        rr.has.CopyFrom(resp.gets)
        reqs.append(req)
    return reqs


async def _drive_per_request(server, reqs):
    out = {}
    for req in reqs:
        out[req.client_id] = await server.GetCapacity(req, None)
    return out


async def _drive_coalesced(server, reqs):
    # Tasks created in submission order park in one window (the test
    # window is far longer than task startup), so arrival order is the
    # per-request stream's order.
    tasks = [
        asyncio.create_task(server.GetCapacity(req, None)) for req in reqs
    ]
    outs = await asyncio.gather(*tasks)
    return {req.client_id: out for req, out in zip(reqs, outs)}


def _store_rows(server):
    return {
        rid: sorted(res.store.dump_rows())
        for rid, res in server.resources.items()
    }


def _native_available():
    from doorman_tpu import native

    return native.native_available()


@pytest.mark.parametrize("native_store", [False, True],
                         ids=["python-store", "native-store"])
def test_coalescing_parity(native_store):
    if native_store and not _native_available():
        pytest.skip("native store engine unavailable")

    async def body():
        clock = FakeClock(1_000.0)
        ref, _ = await make_server(clock=clock, native_store=native_store)
        adm = Admission(coalesce_window=0.05)
        coal, _ = await make_server(
            admission=adm, clock=clock, native_store=native_store
        )
        try:
            prev_ref = await _drive_per_request(
                ref, _round_requests(0)
            )
            prev_coal = await _drive_coalesced(coal, _round_requests(0))
            # Round 2: refreshes carrying each path's own round-1
            # grants (identical if round 1 was), on a later clock.
            clock.t += 5.0
            out_ref = await _drive_per_request(
                ref, _round_requests(1, prev_ref)
            )
            out_coal = await _drive_coalesced(
                coal, _round_requests(1, prev_coal)
            )
            for rnd_ref, rnd_coal in (
                (prev_ref, prev_coal), (out_ref, out_coal),
            ):
                assert {
                    cid: r.SerializeToString()
                    for cid, r in rnd_ref.items()
                } == {
                    cid: r.SerializeToString()
                    for cid, r in rnd_coal.items()
                }
            assert _store_rows(ref) == _store_rows(coal)
            # The windows really coalesced (not 12 one-request flushes).
            assert adm.coalescer.max_occupancy == 6
            assert adm.coalescer.coalesced_requests == 12
        finally:
            await ref.stop()
            await coal.stop()

    run(body())


def test_coalesced_mastership_flip_redirects_parked_requests():
    async def body():
        adm = Admission(coalesce_window=0.05)
        server, _ = await make_server(admission=adm)
        try:
            req = _round_requests(0)[0]
            task = asyncio.create_task(server.GetCapacity(req, None))
            await asyncio.sleep(0)  # task parks in the window
            server.is_master = False
            out = await task
            assert out.HasField("mastership")
            assert not out.response
        finally:
            await server.stop()

    run(body())


# ----------------------------------------------------------------------
# Shedding over real gRPC + the retry-after contract
# ----------------------------------------------------------------------


def _request(client_id, rid="fair", wants=5.0, priority=0):
    req = pb.GetCapacityRequest(client_id=client_id)
    rr = req.resource.add()
    rr.resource_id = rid
    rr.wants = wants
    rr.priority = priority
    return req


def test_shed_carries_retry_after_and_never_sheds_releases():
    async def body():
        # window 100s + max_rps tiny: after 2 admits everything sheds
        # for the rest of the test (deterministic, no level math).
        adm = Admission(
            coalesce_window=0.0, max_rps=0.02, window=100.0,
            rng=random.Random(0),
        )
        server, addr = await make_server(admission=adm)
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                hints = []
                ok = 0
                for i in range(8):
                    try:
                        await stub.GetCapacity(_request(f"s{i}"))
                        ok += 1
                    except grpc.aio.AioRpcError as e:
                        assert (
                            e.code()
                            == grpc.StatusCode.RESOURCE_EXHAUSTED
                        )
                        hints += [
                            float(v)
                            for k, v in e.trailing_metadata() or ()
                            if k == RETRY_AFTER_KEY
                        ]
                assert ok == 2 and len(hints) == 6
                assert all(h > 0 for h in hints)
                # The never-shed rows of the matrix stay served under
                # the same overload.
                out = await stub.ReleaseCapacity(
                    pb.ReleaseCapacityRequest(
                        client_id="s0", resource_id=["fair"]
                    )
                )
                assert not out.HasField("mastership")
                gsc = pb.GetServerCapacityRequest(server_id="downstream")
                rr = gsc.resource.add()
                rr.resource_id = "fair"
                band = rr.wants.add()
                band.priority = 1
                band.num_clients = 2
                band.wants = 8.0
                out = await stub.GetServerCapacity(gsc)
                assert len(out.response) == 1
            tallies = server._admission.tallies
            assert tallies[("GetCapacity", 0)]["shed"] == 6
            assert tallies[("ReleaseCapacity", 0)]["shed"] == 0
            assert tallies[("GetServerCapacity", 1)]["shed"] == 0
        finally:
            await server.stop()

    run(body())


def test_client_honors_retry_after_with_jitter_and_keeps_lease():
    async def body():
        adm = Admission(
            coalesce_window=0.0, max_rps=0.01, window=100.0,
            rng=random.Random(0),
        )
        server, addr = await make_server(admission=adm)
        client = Client(
            addr, "jit", minimum_refresh_interval=0.0, max_retries=0
        )
        try:
            await client.resource("fair", 5.0)
            interval, retry = await client._perform_requests(0)
            assert retry == 0  # first refresh admitted (budget 1)
            res = client.resources["fair"]
            granted = res.current_capacity()
            assert granted == 5.0
            # Every further refresh sheds; the interval obeys the
            # server hint (half jitter: in [hint/2, hint]) and the
            # lease — and the believed capacity — are retained.
            hint = server._admission.controller.retry_after(0)
            for _ in range(3):
                interval, retry = await client._perform_requests(0)
                assert retry == 1
                assert 0.5 * hint <= interval <= hint
                assert res.lease is not None
                assert res.current_capacity() == granted
        finally:
            await client.close()
            await server.stop()

    run(body())


def test_deadline_fast_fail_over_grpc():
    async def body():
        # A long coalescing window: any RPC deadline shorter than it
        # must fast-fail instead of parking.
        adm = Admission(coalesce_window=0.5)
        server, addr = await make_server(admission=adm)
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                with pytest.raises(grpc.aio.AioRpcError) as excinfo:
                    await stub.GetCapacity(
                        _request("dl", priority=2), timeout=0.1
                    )
                assert (
                    excinfo.value.code()
                    == grpc.StatusCode.RESOURCE_EXHAUSTED
                )
            tallies = server._admission.tallies
            assert tallies[("GetCapacity", 2)]["fast_fail"] == 1
            # A deadline fast-fail is the request's own fault, never an
            # overload shed — the top-band goodput floor is untouched.
            assert tallies[("GetCapacity", 2)]["shed"] == 0
        finally:
            await server.stop()

    run(body())


# ----------------------------------------------------------------------
# Observability
# ----------------------------------------------------------------------


def test_debug_admission_page_and_status():
    import json
    import urllib.request

    from doorman_tpu.obs import DebugServer

    async def body():
        adm = Admission(coalesce_window=0.0)
        server, addr = await make_server(admission=adm)
        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)
            await stub.GetCapacity(_request("dbg", priority=1))
        st = server.status()["admission"]
        assert st["controller"]["level"] == 1.0
        assert st["tallies"]["GetCapacity/1"]["admitted"] == 1
        await server.stop()
        return server

    server = run(body())
    debug = DebugServer(port=0)
    debug.add_server(server, None)
    debug.start()
    try:
        html_page = urllib.request.urlopen(
            f"http://127.0.0.1:{debug.port}/debug/admission", timeout=5
        ).read().decode()
        assert "level" in html_page and "GetCapacity/1" in html_page
        js = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{debug.port}/debug/admission?format=json",
            timeout=5,
        ).read().decode())
        assert js["adm-test"]["tallies"]["GetCapacity/1"]["admitted"] == 1
        index = urllib.request.urlopen(
            f"http://127.0.0.1:{debug.port}/debug", timeout=5
        ).read().decode()
        assert "/debug/admission" in index
    finally:
        debug.stop()


def test_admission_metrics_in_default_registry():
    from doorman_tpu.obs import default_registry

    async def body():
        adm = Admission(coalesce_window=0.0)
        server, addr = await make_server(admission=adm)
        try:
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                await stub.GetCapacity(_request("met", priority=3))
        finally:
            await server.stop()

    run(body())
    text = default_registry().expose()
    assert "doorman_admission_requests" in text
    assert (
        'doorman_admission_requests{method="GetCapacity",band="3",'
        'outcome="admitted"}' in text
    )
    assert "doorman_admission_window_occupancy" in text


def test_level_recovers_within_documented_window_on_chaos_clock():
    """Regression pin for the documented recovery window (doc/
    admission.md): from the floor, full admission returns within
    ceil((1 - min_level) / ai_step) healthy control windows — 10 with
    defaults. Driven by the ChaosClock so the windows are exact."""
    import math

    from doorman_tpu.chaos.clock import ChaosClock

    clock = ChaosClock()
    ctl = AimdController(
        window=1.0, clock=clock, rng=random.Random(0), max_rps=10.0
    )
    # Storm to the floor: 40 arrivals/window until min_level holds.
    for _ in range(8):
        for _ in range(40):
            ctl.admit(0)
        clock.advance(1.0)
    assert ctl.level == ctl.min_level
    budget = math.ceil((1.0 - ctl.min_level) / ctl.ai_step)
    # Healthy windows: one calm arrival each; the level must be back
    # at 1.0 within the documented budget (one extra window closes the
    # last storm window's rate).
    for k in range(budget + 1):
        ctl.admit(0)
        clock.advance(1.0)
        if ctl.level == 1.0:
            break
    assert ctl.level == 1.0, (k, ctl.level)
    assert k <= budget, (k, budget)


def test_forecast_seam_folds_into_pressure():
    """The workload forecaster's seam: a demand forecast above max_rps
    multiplies the level down at the NEXT boundary even though the
    observed rate is calm — and clearing the forecast restores the
    purely reactive controller."""
    from doorman_tpu.chaos.clock import ChaosClock

    clock = ChaosClock()
    ctl = AimdController(
        window=1.0, clock=clock, rng=random.Random(0), max_rps=10.0
    )
    for _ in range(3):
        ctl.admit(0)
        clock.advance(1.0)
    assert ctl.level == 1.0
    ctl.set_forecast(30.0)  # 3x the budget, observed rate still calm
    ctl.admit(0)
    clock.advance(1.0)
    ctl.admit(0)  # boundary: pressure = forecast/max_rps = 3 -> MD
    assert ctl.level < 1.0
    level_after_md = ctl.level
    ctl.set_forecast(None)
    for _ in range(15):
        ctl.admit(0)
        clock.advance(1.0)
    assert ctl.level == 1.0  # reactive again, recovered
    assert level_after_md < 1.0
    # status() reports the seam for debug pages.
    ctl.set_forecast(12.5)
    assert ctl.status()["forecast_rps"] == 12.5
