"""End-to-end server coverage of the WIDE resident path: a lane
resource wider than the dense bucket cap partitions onto the chunked
solver (solver/resident_wide.py) from the very first eligibility check
— no ResidentOverflow round-trip — and serves correct, capacity-safe
grants over real gRPC, mixed alongside narrow resources on the narrow
resident solver.

DENSE_MAX_K is monkeypatched small so the boundary is exercised with
test-sized populations; boundary widths (cap, cap+1) pin the partition
edge itself."""

import asyncio
import time

import grpc
import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import CapacityStub
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

CONFIG = """
resources:
- identifier_glob: "wide"
  capacity: 1000
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 500
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""


def patch_cap(monkeypatch, cap):
    """The dense bucket cap is read at call time in the three modules
    that partition or overflow on it."""
    import doorman_tpu.solver.batch as batch_mod
    import doorman_tpu.solver.resident as resident_mod
    import doorman_tpu.solver.resident_wide as wide_mod

    monkeypatch.setattr(batch_mod, "DENSE_MAX_K", cap)
    monkeypatch.setattr(resident_mod, "DENSE_MAX_K", cap)
    monkeypatch.setattr(wide_mod, "DENSE_MAX_K", cap)


def bulk_load(server, resource_id, n, wants=5.0):
    engine = server._store_factory.__self__
    res = server.resources[resource_id]
    rids = np.full(n, res.store._rid, np.int32)
    cids = np.array(
        [engine.client_handle(f"bulk_{resource_id}_{i}") for i in range(n)],
        np.int64,
    )
    engine.bulk_assign(
        rids, cids, np.full(n, time.time() + 60.0),
        np.full(n, 1.0), np.zeros(n),
        np.full(n, wants), np.ones(n, np.int32),
    )
    return res


def test_wide_resource_partitions_to_chunked_solver(monkeypatch):
    patch_cap(monkeypatch, 16)

    async def body():
        server = CapacityServer(
            "widesrv", TrivialElection(), mode="batch",
            tick_interval=0.05, minimum_refresh_interval=0.0,
            native_store=True,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        server.current_master = f"127.0.0.1:{port}"
        addr = f"127.0.0.1:{port}"

        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)

            def request(i, resource, wants):
                req = pb.GetCapacityRequest(client_id=f"c{i}")
                rr = req.resource.add()
                rr.resource_id = resource
                rr.wants = wants
                return req

            # Prime both resources over gRPC, then bulk-grow "wide"
            # past the (patched) cap BEFORE the first tick partitions.
            await stub.GetCapacity(request(0, "wide", 5.0))
            await stub.GetCapacity(request(0, "narrow", 5.0))
            res = bulk_load(server, "wide", 40, wants=40.0)
            assert len(res.store) > 16

            for _ in range(200):
                if (
                    server._resident_wide is not None
                    and server._resident_wide.ticks >= 3
                    and server._resident is not None
                    and server._resident.ticks >= 3
                ):
                    break
                await asyncio.sleep(0.05)
            # Partitioned directly — no overflow fallback needed.
            assert server._resident_wide is not None
            assert server._resident_wide.ticks >= 3
            assert "wide" in server._wide_ids
            # The narrow resource kept the narrow resident solver.
            assert server._resident is not None
            assert server._resident.ticks >= 3
            assert "narrow" not in server._wide_ids

            # Oversubscribed proportional share: grants scale to
            # capacity; the store conserves exactly.
            out = await stub.GetCapacity(request(0, "wide", 40.0))
            got = out.response[0].gets.capacity
            assert 0.0 <= got <= 40.0
            assert res.store.sum_has <= 1000.0 + 1e-6
            leases = dict(res.store.items())
            lease_sum = sum(l.has for l in leases.values())
            assert abs(lease_sum - res.store.sum_has) < 1e-6

        await server.stop()

    asyncio.run(body())


@pytest.mark.parametrize("width,expect_wide", [(16, False), (17, True)])
def test_partition_boundary(monkeypatch, width, expect_wide):
    """Exactly at the cap stays narrow; one past it goes wide."""
    patch_cap(monkeypatch, 16)

    async def body():
        server = CapacityServer(
            "boundary", TrivialElection(), mode="batch",
            tick_interval=0.05, minimum_refresh_interval=0.0,
            native_store=True,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        server.current_master = f"127.0.0.1:{port}"
        addr = f"127.0.0.1:{port}"

        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)
            req = pb.GetCapacityRequest(client_id="c0")
            rr = req.resource.add()
            rr.resource_id = "wide"
            rr.wants = 5.0
            await stub.GetCapacity(req)
            res = bulk_load(server, "wide", width - 1, wants=10.0)
            assert len(res.store) == width

            deadline = time.time() + 10.0
            while time.time() < deadline:
                solver = (
                    server._resident_wide
                    if expect_wide
                    else server._resident
                )
                if solver is not None and solver.ticks >= 2:
                    break
                await asyncio.sleep(0.05)
            assert ("wide" in server._wide_ids) == expect_wide
            solver = (
                server._resident_wide if expect_wide else server._resident
            )
            assert solver is not None and solver.ticks >= 2
            # Demand fits capacity: everyone gets wants, conserved.
            assert res.store.sum_has <= 1000.0 + 1e-6

        await server.stop()

    asyncio.run(body())
