"""Fused device-resident tick: byte-identity, dispatch accounting, and
the fused pallas kernel's interpret-mode parity.

The fused tick (solver/resident.py / resident_wide.py fused tails) runs
one packed staged upload + ONE staging->solve->delta launch + one
download stream per tick instead of a device dispatch per staged block.
This suite pins the three claims that make it shippable:

  * byte-identity: fused vs round-trip stores are IDENTICAL over churn
    that mixes bf16-exact and non-exact wants, across all four resident
    paths (narrow/wide x single-device/mesh — the mesh legs run under
    the forced 8-device CPU platform), with the delta-tracking
    changed-rid stream (what the streaming push fans out from) equal
    too — so the push sequence cannot differ;
  * accounting: the per-tick `dispatches`/`host_syncs` counters through
    the utils.dispatch chokepoints drop from 5/2 to 2/1 on a
    steady-state tracked tick (the bench-scale reduction is larger —
    the round-trip download splits into several counted streams);
  * kernels: pallas_dense.fused_tick_pallas (solve + delta compare +
    prev update in one VMEM pass) matches solve_dense + the host delta
    reference bit-for-bit in interpret mode over every AlgoKind lane,
    including the compact water-fill restriction and learning-mode
    replay; the band-masked priority kernel parity rides
    tests/test_pallas_priority.py.

Donation-reuse regression: every parity run steps the fused executable
repeatedly through the `x = f(x)` rebind pattern — a donation bug dies
loudly (XLA refuses a donated buffer's reuse), so the multi-step runs
ARE the regression test.
"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.parallel import make_mesh
from doorman_tpu.solver.engine import PHASES
from doorman_tpu.solver.resident import ResidentDenseSolver
from doorman_tpu.solver.resident_wide import WideResidentSolver
from doorman_tpu.utils import dispatch as dispatch_mod
from tests.test_engine import assert_store_parity, conformance_churn
from tests.test_resident_solver import all_leases, make_world

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

FUSED_PATHS = ("resident", "resident_mesh", "wide", "wide_mesh")


def _make(path, engine, clock, fused):
    mesh = make_mesh() if path.endswith("_mesh") else None
    if path.startswith("resident"):
        return ResidentDenseSolver(
            engine, dtype=np.float64, clock=clock, rotate_ticks=1,
            mesh=mesh, fused=fused,
        )
    return WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8, mesh=mesh, fused=fused,
    )


@pytest.mark.parametrize("path", FUSED_PATHS)
def test_fused_vs_roundtrip_byte_identity(path):
    """The load-bearing pin: one churn stream (mixing bf16-exact and
    non-exact wants — both fused buffer encodings compile and run),
    fused and round-trip solvers compared store-for-store every tick.
    Narrow paths additionally run delta tracking and must emit the SAME
    changed-rid stream (the streaming push's input — equal rids means
    the push sequence cannot differ), and the repeated fused steps are
    the donation-reuse (`x = f(x)` rebind) regression."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    plain = _make(path, eng_a, clock, fused=False)
    fused = _make(path, eng_b, clock, fused=True)
    assert fused.fused_tick and not plain.fused_tick
    track = path.startswith("resident")
    if track:
        assert plain.enable_delta_tracking()
        assert fused.enable_delta_tracking()
    rng_a, rng_b = (np.random.default_rng(17) for _ in range(2))
    for step in range(8):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        if step == 4:
            # Learning-mode flip mid-run: the config epoch bump drives
            # the full-delivery path through the fused executable too.
            res_a[2].learning_mode_end = t[0] + 2.5
            res_b[2].learning_mode_end = t[0] + 2.5
        epoch = 1 if step >= 4 else 0
        plain.step(res_a, epoch)
        fused.step(res_b, epoch)
        ref, got = all_leases(res_a), all_leases(res_b)
        # Fused vs round-trip is exact on every path (same executable
        # math, only the transfer packing differs) — the wide paths'
        # reassociation tolerance applies vs the BatchSolver, not here.
        assert ref.keys() == got.keys(), f"{path} step {step}"
        for key in ref:
            assert got[key] == ref[key], (
                f"{path} step {step} lease {key}: "
                f"{got[key]} != {ref[key]}"
            )
        if track:
            assert (
                sorted(plain.take_changed_rids())
                == sorted(fused.take_changed_rids())
            ), f"{path} step {step}: changed-rid streams diverged"
        t[0] += 1.0
    # Both bf16 encodings actually compiled (the churn alternates
    # exact/non-exact wants). The bf16 flag sits last in the narrow
    # fused keys (full and scoped) and before the index dtype in the
    # wide fused keys.
    bf_at = -1 if path.startswith("resident") else -2
    fused_keys = [k for k in fused._tick_fns if k[0].startswith("fused")]
    assert {k[bf_at] for k in fused_keys} == {True, False}, fused_keys


def test_fused_matches_batch_ground_truth():
    """Fused narrow stores also match the BatchSolver oracle world (the
    conformance suite's ground truth), so fusion cannot drift from the
    reference math even if both resident paths drifted together."""
    from doorman_tpu.solver.batch import BatchSolver
    from doorman_tpu.solver.engine import BatchTickAdapter

    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    batch = BatchTickAdapter(BatchSolver(dtype=np.float64, clock=clock))
    fused = _make("resident", eng_b, clock, fused=True)
    rng_a, rng_b = (np.random.default_rng(23) for _ in range(2))
    for step in range(6):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        batch.step(res_a, 0)
        fused.step(res_b, 0)
        assert_store_parity(
            all_leases(res_a), all_leases(res_b), "resident",
            f"step {step}",
        )
        t[0] += 1.0


def test_fused_phase_vocabulary():
    """Fused ticks lap the registered "fused" phase (the single
    placement + launch + download kickoff) instead of upload/solve;
    the round-trip mode keeps upload/solve and never laps "fused"."""
    assert "fused" in PHASES
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    plain = _make("resident", eng_a, clock, fused=False)
    fused = _make("resident", eng_b, clock, fused=True)
    for solver, res in ((plain, res_a), (fused, res_b)):
        for step in range(2):
            res[0].store.assign(
                "c0_0", 60.0, 5.0, res[0].store.get("c0_0").has,
                5.0 + step, 1,
            )
            solver.step(res, 0)
            t[0] += 1.0
    assert fused.phase_s["fused"] > 0.0
    assert fused.phase_s["upload"] == 0.0
    assert fused.phase_s["solve"] == 0.0
    assert plain.phase_s["fused"] == 0.0
    assert plain.phase_s["upload"] > 0.0
    assert plain.phase_s["solve"] > 0.0


def test_dispatch_accounting_steady_tick():
    """The accounting chokepoints see exactly the documented per-tick
    shape at test scale: a steady-state tracked round-trip tick costs
    4 staged placements + 1 launch = 5 dispatches and 2 host syncs
    (grant slab + changed mask); the fused tick costs 1 placement +
    1 launch = 2 dispatches and 1 sync (mask packed into the slab).
    At bench scale the round-trip download additionally splits into
    several counted streams, so the reduction there is larger."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    counts = {}
    for fused in (False, True):
        engine, resources = make_world(clock)
        solver = _make("resident", engine, clock, fused=fused)
        # The PR-13 dispatch floor is pinned on the FULL fused
        # executable; the scoped tick's own counts (3 while the scope
        # changes, back to 2 at the quiet-tick fixpoint via the scope
        # buffer cache) are pinned in tests/test_scoped_solve.py.
        solver.scoped_solve = False
        solver.enable_delta_tracking()
        rng = np.random.default_rng(5)
        for step in range(3):  # build + settle both executables
            conformance_churn(resources, step, rng)
            solver.step(resources, 0)
            t[0] += 1.0
        conformance_churn(resources, 3, rng)
        before = dispatch_mod.snapshot()
        solver.step(resources, 0)
        counts[fused] = dispatch_mod.delta(before)
        t[0] += 1.0
    assert counts[True]["dispatches"] == 2, counts
    assert counts[False]["dispatches"] == 5, counts
    assert counts[True]["host_syncs"] == 1, counts
    assert counts[False]["host_syncs"] == 2, counts
    # The acceptance direction, stated as a ratio: >= 2.5x at test
    # scale, >= 3x at bench scale where the split download counts.
    assert (
        counts[False]["dispatches"] >= 2.5 * counts[True]["dispatches"]
    )


def test_fused_toggle_rebuilds_executables():
    """Flipping fused_tick at runtime drops the cached executables and
    both modes keep producing identical stores (triage flow: flip a
    live server to round-trip mode without a restart)."""
    t = [1000.0]
    clock = lambda: t[0]  # noqa: E731
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    ref = _make("resident", eng_a, clock, fused=False)
    toggled = _make("resident", eng_b, clock, fused=True)
    rng_a, rng_b = (np.random.default_rng(31) for _ in range(2))
    for step in range(6):
        conformance_churn(res_a, step, rng_a)
        conformance_churn(res_b, step, rng_b)
        if step == 3:
            toggled.fused_tick = False
        ref.step(res_a, 0)
        toggled.step(res_b, 0)
        ref_rows, got_rows = all_leases(res_a), all_leases(res_b)
        assert ref_rows == got_rows, f"step {step}"
        t[0] += 1.0
    assert not toggled.fused_tick


# ----------------------------------------------------------------------
# Fused pallas kernel (interpret mode — the CPU parity path)
# ----------------------------------------------------------------------


def _random_batch(rng, R, K, kinds, dtype=np.float32):
    import jax.numpy as jnp

    from doorman_tpu.solver.dense import DenseBatch

    return DenseBatch(
        wants=jnp.asarray(rng.integers(0, 60, (R, K)).astype(dtype)),
        has=jnp.asarray(rng.integers(0, 25, (R, K)).astype(dtype)),
        subclients=jnp.asarray(
            rng.integers(1, 4, (R, K)).astype(dtype)
        ),
        active=jnp.asarray(rng.random((R, K)) < 0.8),
        capacity=jnp.asarray(
            rng.integers(10, 400, R).astype(dtype)
        ),
        algo_kind=jnp.asarray(kinds.astype(np.int32)),
        learning=jnp.asarray(rng.random(R) < 0.2),
        static_capacity=jnp.asarray(
            rng.integers(1, 12, R).astype(dtype)
        ),
    )


def test_fused_pallas_kernel_all_lanes_parity():
    """fused_tick_pallas over every AlgoKind lane (incl. learning
    replay): its grants are BIT-identical to solve_dense_pallas (the
    unfused TPU solve it replaces — same kernel body, so the fused TPU
    tick cannot move a grant), within the established kernel-vs-XLA
    tolerance of solve_dense (the lane-padded f32 reduction order
    differs, exactly as tests/test_pallas_dense.py pins), and the
    delta/prev outputs are bit-consistent with its own grants:
    changed = delivered AND any-lane moved, prev advances delivered
    rows only."""
    import jax.numpy as jnp

    from doorman_tpu.algorithms.kinds import AlgoKind
    from doorman_tpu.solver.dense import solve_dense
    from doorman_tpu.solver.pallas_dense import (
        fused_tick_pallas,
        solve_dense_pallas,
    )

    rng = np.random.default_rng(11)
    R, K = 40, 24
    kinds = rng.choice(
        [
            int(k)
            for k in (
                AlgoKind.NO_ALGORITHM,
                AlgoKind.STATIC,
                AlgoKind.PROPORTIONAL_SHARE,
                AlgoKind.FAIR_SHARE,
                AlgoKind.PROPORTIONAL_TOPUP,
                # The fairness portfolio rides the same kernel body:
                # its bounded fills must hold the same fused-vs-unfused
                # bit identity and kernel-vs-XLA tolerance.
                AlgoKind.MAX_MIN_FAIR,
                AlgoKind.BALANCED_FAIRNESS,
                AlgoKind.PROPORTIONAL_FAIRNESS,
            )
        ],
        R,
    )
    batch = _random_batch(rng, R, K, kinds)
    prev = jnp.asarray(rng.integers(0, 30, (R, K)).astype(np.float32))
    delivered = jnp.asarray((rng.random(R) < 0.5).astype(np.float32))

    gets, prev_new, changed = fused_tick_pallas(
        batch, prev, delivered, interpret=True
    )
    gets = np.asarray(gets)
    # Bit-identical to the unfused pallas solve it replaces.
    np.testing.assert_array_equal(
        gets, np.asarray(solve_dense_pallas(batch, interpret=True))
    )
    # Within the established kernel-vs-XLA tolerance of solve_dense.
    np.testing.assert_allclose(
        gets, np.asarray(solve_dense(batch)), rtol=1e-5, atol=1e-4
    )
    deliv = np.asarray(delivered) > 0
    exp_changed = deliv & (gets != np.asarray(prev)).any(axis=1)
    np.testing.assert_array_equal(np.asarray(changed), exp_changed)
    np.testing.assert_array_equal(
        np.asarray(prev_new),
        np.where(deliv[:, None], gets, np.asarray(prev)),
    )


def test_fused_pallas_kernel_matches_compact_waterfill():
    """FAIR_SHARE rows through the fused kernel agree with the compact
    water-fill restriction (solve_dense with fair_rows — the
    gather->bisect->scatter round trip the fused TPU tick replaces)
    within the established kernel-vs-XLA tolerance, and exactly with
    the unfused pallas kernel."""
    import jax.numpy as jnp

    from doorman_tpu.algorithms.kinds import AlgoKind
    from doorman_tpu.solver.dense import solve_dense
    from doorman_tpu.solver.pallas_dense import (
        fused_tick_pallas,
        solve_dense_pallas,
    )

    rng = np.random.default_rng(13)
    R, K = 32, 16
    kinds = np.full(R, int(AlgoKind.PROPORTIONAL_SHARE))
    fair = rng.choice(R, 10, replace=False)
    kinds[fair] = int(AlgoKind.FAIR_SHARE)
    batch = _random_batch(rng, R, K, kinds)
    fair_rows = jnp.asarray(
        np.resize(np.sort(fair), 16).astype(np.int32)
    )
    compact = np.asarray(
        solve_dense(
            batch,
            lanes=frozenset(int(k) for k in np.unique(kinds)),
            fair_rows=fair_rows,
        )
    )
    prev = jnp.zeros((R, K), jnp.float32)
    delivered = jnp.ones(R, jnp.float32)
    gets, _, changed = fused_tick_pallas(
        batch, prev, delivered, interpret=True
    )
    gets = np.asarray(gets)
    np.testing.assert_array_equal(
        gets, np.asarray(solve_dense_pallas(batch, interpret=True))
    )
    np.testing.assert_allclose(gets, compact, rtol=1e-5, atol=1e-4)
    # Full delivery against a zero prev: changed wherever grants are
    # nonzero.
    np.testing.assert_array_equal(
        np.asarray(changed), (gets != 0).any(axis=1)
    )


def test_fused_pallas_kernel_bf16_exact_and_not():
    """Both fused-buffer wants encodings feed the same kernel output:
    bf16-exact wants (small integers) and non-exact wants (thirds)
    solve identically whether the host shipped them compact or full
    width — the cast back is the identity exactly when bf16_exact said
    so."""
    import jax.numpy as jnp
    from ml_dtypes import bfloat16

    from doorman_tpu.algorithms.kinds import AlgoKind
    from doorman_tpu.solver.engine import bf16_exact
    from doorman_tpu.solver.pallas_dense import fused_tick_pallas

    rng = np.random.default_rng(19)
    R, K = 16, 8
    kinds = np.full(R, int(AlgoKind.PROPORTIONAL_SHARE))
    exact = rng.integers(0, 100, (R, K)).astype(np.float32)
    assert bf16_exact(exact)
    inexact = exact + np.float32(1.0 / 3.0)
    assert not bf16_exact(inexact)
    for wants, is_exact in ((exact, True), (inexact, False)):
        shipped = (
            wants.astype(bfloat16).astype(np.float32)
            if is_exact
            else wants
        )
        np.testing.assert_array_equal(shipped, wants) if is_exact else None
        batch = _random_batch(rng, R, K, kinds)
        batch = type(batch)(
            wants=jnp.asarray(shipped),
            has=batch.has,
            subclients=batch.subclients,
            active=batch.active,
            capacity=batch.capacity,
            algo_kind=batch.algo_kind,
            learning=batch.learning,
            static_capacity=batch.static_capacity,
        )
        gets, _, _ = fused_tick_pallas(
            batch,
            jnp.zeros((R, K), jnp.float32),
            jnp.ones(R, jnp.float32),
            interpret=True,
        )
        from doorman_tpu.solver.dense import solve_dense

        np.testing.assert_array_equal(
            np.asarray(gets), np.asarray(solve_dense(batch))
        )
