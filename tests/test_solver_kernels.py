"""Parity tests: the batched JAX solve must reproduce the numpy tick
oracles exactly (inputs are integer-valued f64, so every sum/division in
both implementations is computed on identical representable values)."""

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (env vars before jax import)
import jax.numpy as jnp

from doorman_tpu.algorithms import tick
from doorman_tpu.solver import AlgoKind, EdgeBatch, ResourceBatch, solve_tick
from doorman_tpu.solver.kernels import proportional_sequential_dense


def build_batch(tables, *, pad_edges=0, pad_resources=0, dtype=np.float64):
    """tables: list of dicts with capacity, kind, wants[], has[], sub[],
    optional static_cap, learning."""
    rid, wants, has, sub = [], [], [], []
    for r, t in enumerate(tables):
        for i in range(len(t["wants"])):
            rid.append(r)
            wants.append(t["wants"][i])
            has.append(t.get("has", [0.0] * len(t["wants"]))[i])
            sub.append(t.get("sub", [1.0] * len(t["wants"]))[i])
    E = len(rid) + pad_edges
    R = len(tables) + pad_resources
    active = np.zeros(E, dtype=bool)
    active[: len(rid)] = True
    pad = lambda xs, fill: np.array(
        list(xs) + [fill] * (E - len(xs)), dtype=dtype
    )
    edges = EdgeBatch(
        resource=jnp.array(
            np.array(rid + [R - 1] * pad_edges, dtype=np.int32)
        ),
        wants=jnp.array(pad(wants, 0.0)),
        has=jnp.array(pad(has, 0.0)),
        subclients=jnp.array(pad(sub, 0.0)),
        active=jnp.array(active),
    )
    rpad = lambda xs, fill: np.array(
        list(xs) + [fill] * (R - len(xs)), dtype=dtype
    )
    resources = ResourceBatch(
        capacity=jnp.array(rpad([t["capacity"] for t in tables], 0.0)),
        algo_kind=jnp.array(
            np.array(
                [int(t["kind"]) for t in tables] + [0] * pad_resources,
                dtype=np.int32,
            )
        ),
        learning=jnp.array(
            np.array(
                [t.get("learning", False) for t in tables]
                + [False] * pad_resources
            )
        ),
        static_capacity=jnp.array(
            rpad([t.get("static_cap", 0.0) for t in tables], 0.0)
        ),
    )
    return edges, resources


def oracle_for(t):
    wants = np.array(t["wants"], dtype=np.float64)
    has = np.array(t.get("has", [0.0] * len(wants)), dtype=np.float64)
    sub = np.array(t.get("sub", [1.0] * len(wants)), dtype=np.float64)
    if t.get("learning"):
        return tick.learn_tick(has)
    kind = t["kind"]
    if kind == AlgoKind.NO_ALGORITHM:
        return tick.none_tick(wants)
    if kind == AlgoKind.STATIC:
        return tick.static_tick(t["static_cap"], wants)
    if kind == AlgoKind.PROPORTIONAL_SHARE:
        return tick.proportional_snapshot(t["capacity"], wants, has)
    if kind == AlgoKind.PROPORTIONAL_TOPUP:
        return tick.proportional_topup_snapshot(t["capacity"], wants, has, sub)
    if kind == AlgoKind.FAIR_SHARE:
        return tick.fair_share_waterfill(t["capacity"], wants, sub)
    raise ValueError(kind)


def check_tables(tables, **kw):
    edges, resources = build_batch(tables, **kw)
    gets = np.asarray(solve_tick(edges, resources))
    i = 0
    for r, t in enumerate(tables):
        n = len(t["wants"])
        expected = oracle_for(t)
        np.testing.assert_array_equal(
            gets[i : i + n],
            expected,
            err_msg=f"resource {r} (kind={t['kind']})",
        )
        i += n
    # padding produced zeros
    assert np.all(gets[i:] == 0.0)


def test_single_resource_each_kind():
    base = {"wants": [60.0, 60.0, 10.0], "capacity": 120.0}
    check_tables([{**base, "kind": AlgoKind.NO_ALGORITHM}])
    check_tables([{**base, "kind": AlgoKind.STATIC, "static_cap": 50.0}])
    check_tables([{**base, "kind": AlgoKind.PROPORTIONAL_SHARE}])
    check_tables([{**base, "kind": AlgoKind.PROPORTIONAL_TOPUP}])
    check_tables([{**base, "kind": AlgoKind.FAIR_SHARE}])


def test_go_reference_tables_topup():
    # algorithm_test.go TestProportionalShare / WithMultipleSubclients
    # (preloaded): [55, 55, 10] and [60, 40, 20].
    edges, resources = build_batch(
        [
            {
                "kind": AlgoKind.PROPORTIONAL_TOPUP,
                "capacity": 120.0,
                "wants": [60.0, 60.0, 10.0],
            },
            {
                "kind": AlgoKind.PROPORTIONAL_TOPUP,
                "capacity": 120.0,
                "wants": [65.0, 45.0, 20.0],
                "sub": [3.0, 2.0, 1.0],
            },
        ]
    )
    gets = np.asarray(solve_tick(edges, resources))
    np.testing.assert_allclose(gets[:3], [55.0, 55.0, 10.0])
    np.testing.assert_allclose(gets[3:6], [60.0, 40.0, 20.0])


def test_go_reference_tables_fairshare():
    tables = [
        {"kind": AlgoKind.FAIR_SHARE, "capacity": 120.0, "wants": [1000.0, 60.0, 10.0]},
        {"kind": AlgoKind.FAIR_SHARE, "capacity": 120.0, "wants": [1000.0, 50.0, 10.0]},
        {
            "kind": AlgoKind.FAIR_SHARE,
            "capacity": 120.0,
            "wants": [1000.0, 500.0, 200.0],
            "sub": [6.0, 4.0, 2.0],
        },
        {
            "kind": AlgoKind.FAIR_SHARE,
            "capacity": 1000.0,
            "wants": [2000.0, 500.0, 700.0],
            "sub": [10.0, 10.0, 30.0],
        },
    ]
    edges, resources = build_batch(tables)
    gets = np.asarray(solve_tick(edges, resources))
    np.testing.assert_allclose(gets[0:3], [55.0, 55.0, 10.0])
    np.testing.assert_allclose(gets[3:6], [60.0, 50.0, 10.0])
    np.testing.assert_allclose(gets[6:9], [60.0, 40.0, 20.0])
    np.testing.assert_allclose(gets[9:12], [200.0, 200.0, 600.0])


def test_learning_mode_overrides_lane():
    check_tables(
        [
            {
                "kind": AlgoKind.FAIR_SHARE,
                "capacity": 10.0,
                "wants": [100.0, 200.0],
                "has": [7.0, 3.0],
                "learning": True,
            }
        ]
    )


@pytest.mark.parametrize("seed", range(5))
def test_randomized_mixed_batch_bit_parity(seed):
    rng = np.random.default_rng(seed)
    kinds = [
        AlgoKind.NO_ALGORITHM,
        AlgoKind.STATIC,
        AlgoKind.PROPORTIONAL_SHARE,
        AlgoKind.PROPORTIONAL_TOPUP,
        AlgoKind.FAIR_SHARE,
    ]
    tables = []
    for _ in range(30):
        n = int(rng.integers(1, 25))
        tables.append(
            {
                "kind": kinds[int(rng.integers(len(kinds)))],
                "capacity": float(rng.integers(1, 500)),
                "static_cap": float(rng.integers(1, 100)),
                "wants": rng.integers(0, 200, n).astype(np.float64).tolist(),
                "has": rng.integers(0, 100, n).astype(np.float64).tolist(),
                "sub": rng.integers(1, 8, n).astype(np.float64).tolist(),
                "learning": bool(rng.integers(0, 10) == 0),
            }
        )
    check_tables(tables, pad_edges=17, pad_resources=3)


def test_property_never_overcommit_fair_and_prop():
    rng = np.random.default_rng(7)
    for _ in range(10):
        n = int(rng.integers(2, 50))
        for kind in (AlgoKind.PROPORTIONAL_SHARE, AlgoKind.FAIR_SHARE):
            t = {
                "kind": kind,
                "capacity": float(rng.integers(10, 300)),
                "wants": rng.integers(0, 100, n).astype(np.float64).tolist(),
                # steady state: has from a previous solve, never overcommitted
                "has": [0.0] * n,
            }
            edges, resources = build_batch([t])
            gets = np.asarray(solve_tick(edges, resources))
            assert gets.sum() <= t["capacity"] + 1e-9 or (
                np.sum(t["wants"]) <= t["capacity"]
            )


def test_equal_share_floor_fairshare():
    # Overloaded fair share: every client asking >= equal share gets >= the
    # equal share (the floor guarantee the reference documents).
    n, cap = 10, 100.0
    wants = (np.ones(n) * 50.0).tolist()
    edges, resources = build_batch(
        [{"kind": AlgoKind.FAIR_SHARE, "capacity": cap, "wants": wants}]
    )
    gets = np.asarray(solve_tick(edges, resources))
    np.testing.assert_allclose(gets[:n], cap / n)


def test_sequential_dense_matches_numpy():
    rng = np.random.default_rng(3)
    R, C = 6, 40
    wants = rng.integers(0, 100, (R, C)).astype(np.float64)
    has = rng.integers(0, 50, (R, C)).astype(np.float64)
    active = rng.random((R, C)) < 0.9
    wants *= active
    has *= active
    cap = rng.integers(50, 2000, R).astype(np.float64)
    gets = np.asarray(
        proportional_sequential_dense(
            jnp.array(cap), jnp.array(wants), jnp.array(has), jnp.array(active)
        )
    )
    for r in range(R):
        idx = np.where(active[r])[0]
        expected = tick.proportional_sequential(
            cap[r], wants[r, idx], has[r, idx]
        )
        np.testing.assert_array_equal(gets[r, idx], expected, err_msg=f"r={r}")
        assert np.all(gets[r, ~active[r]] == 0.0)
