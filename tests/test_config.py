"""Config layer tests; the invalid-repository cases mirror the reference's
validation tests (/root/reference/go/server/doorman/server_test.go:30-127)."""

import pytest

from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.server import config as cfg


def algo(kind=pb.Algorithm.PROPORTIONAL_SHARE, lease=60, refresh=16):
    return pb.Algorithm(kind=kind, lease_length=lease, refresh_interval=refresh)


def repo(*templates):
    r = pb.ResourceRepository()
    r.resources.extend(templates)
    return r


def star(capacity=100.0):
    return pb.ResourceTemplate(
        identifier_glob="*", capacity=capacity, algorithm=algo()
    )


class TestValidateRepository:
    def test_valid_minimal(self):
        cfg.validate_repository(repo(star()))

    def test_missing_star(self):
        with pytest.raises(cfg.ConfigError, match="entry for"):
            cfg.validate_repository(
                repo(pb.ResourceTemplate(identifier_glob="res0", capacity=1.0,
                                         algorithm=algo()))
            )

    def test_star_not_last(self):
        with pytest.raises(cfg.ConfigError, match="last"):
            cfg.validate_repository(
                repo(star(), pb.ResourceTemplate(identifier_glob="res0",
                                                 capacity=1.0, algorithm=algo()))
            )

    def test_star_without_algorithm(self):
        t = pb.ResourceTemplate(identifier_glob="*", capacity=1.0)
        with pytest.raises(cfg.ConfigError, match="algorithm"):
            cfg.validate_repository(repo(t))

    def test_refresh_below_one(self):
        t = pb.ResourceTemplate(
            identifier_glob="*", capacity=1.0, algorithm=algo(refresh=0)
        )
        with pytest.raises(cfg.ConfigError, match="refresh"):
            cfg.validate_repository(repo(t))

    def test_lease_below_refresh(self):
        t = pb.ResourceTemplate(
            identifier_glob="*", capacity=1.0, algorithm=algo(lease=5, refresh=16)
        )
        with pytest.raises(cfg.ConfigError, match="[Ll]ease length"):
            cfg.validate_repository(repo(t))

    def test_malformed_glob(self):
        t = pb.ResourceTemplate(
            identifier_glob="[unterminated", capacity=1.0, algorithm=algo()
        )
        with pytest.raises(cfg.ConfigError, match="glob"):
            cfg.validate_repository(repo(t, star()))


class TestFindTemplate:
    def test_exact_beats_glob(self):
        exact = pb.ResourceTemplate(identifier_glob="res0", capacity=1.0,
                                    algorithm=algo())
        globby = pb.ResourceTemplate(identifier_glob="res*", capacity=2.0,
                                     algorithm=algo())
        r = repo(globby, exact, star())
        assert cfg.find_template(r, "res0").capacity == 1.0

    def test_first_glob_wins(self):
        g1 = pb.ResourceTemplate(identifier_glob="res*", capacity=1.0,
                                 algorithm=algo())
        g2 = pb.ResourceTemplate(identifier_glob="r*", capacity=2.0,
                                 algorithm=algo())
        r = repo(g1, g2, star())
        assert cfg.find_template(r, "res7").capacity == 1.0

    def test_fallback_to_star(self):
        r = repo(star(capacity=42.0))
        assert cfg.find_template(r, "anything").capacity == 42.0


class TestYaml:
    def test_round_trip(self):
        text = """
resources:
- identifier_glob: fair
  capacity: 500
  safe_capacity: 10
  algorithm:
    kind: FAIR_SHARE
    lease_length: 60
    refresh_interval: 16
- identifier_glob: "*"
  capacity: 100
  algorithm:
    kind: PROPORTIONAL_SHARE
    lease_length: 60
    refresh_interval: 16
"""
        r = cfg.parse_yaml_config(text)
        assert len(r.resources) == 2
        assert r.resources[0].algorithm.kind == pb.Algorithm.FAIR_SHARE
        assert r.resources[0].HasField("safe_capacity")
        assert r.resources[0].safe_capacity == 10
        again = cfg.parse_yaml_config(cfg.repository_to_yaml(r))
        assert again == r

    def test_empty_doc(self):
        with pytest.raises(cfg.ConfigError):
            cfg.parse_yaml_config("")

    def test_invalid_yaml(self):
        with pytest.raises(cfg.ConfigError):
            cfg.parse_yaml_config("resources: [}")


class TestValidateRequests:
    def test_empty_client(self):
        req = pb.GetCapacityRequest()
        assert cfg.validate_get_capacity_request(req) is not None

    def test_negative_wants(self):
        req = pb.GetCapacityRequest(client_id="c")
        rr = req.resource.add()
        rr.resource_id = "r"
        rr.wants = -1.0
        assert cfg.validate_get_capacity_request(req) is not None

    def test_ok(self):
        req = pb.GetCapacityRequest(client_id="c")
        rr = req.resource.add()
        rr.resource_id = "r"
        rr.wants = 5.0
        assert cfg.validate_get_capacity_request(req) is None

    def test_server_capacity_bad_subclients(self):
        req = pb.GetServerCapacityRequest(server_id="s")
        rr = req.resource.add()
        rr.resource_id = "r"
        band = rr.wants.add()
        band.priority = 0
        band.num_clients = 0
        band.wants = 10.0
        assert cfg.validate_get_server_capacity_request(req) is not None
