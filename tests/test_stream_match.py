"""SubscriptionMatcher (server/match.py): the device-side changed-row
-> subscriber intersection behind the stream fanout.

Pins: host-mirror and device paths return identical pair sets; the
incremental scatter path (subscribe/unsubscribe churn within extent
headroom) matches a from-scratch rebuild; extent overflow repacks;
slots recycle; and the per-match device work never syncs a shape
(match size is host-known from the mirrored extent lengths).
"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.server.match import SubscriptionMatcher


def pairs_set(pairs):
    return {(int(a), int(b)) for a, b in pairs}


def expected(matcher, changed):
    out = set()
    for rid in changed:
        for slot in matcher._members.get(rid, ()):
            out.add((slot, rid))
    return out


def test_host_mirror_matching():
    m = SubscriptionMatcher(use_device=False)
    s0 = m.add([1, 2, 3])
    s1 = m.add([2])
    s2 = m.add([3, 7])
    assert pairs_set(m.match([2])) == {(s0, 2), (s1, 2)}
    assert pairs_set(m.match([7])) == {(s2, 7)}
    assert pairs_set(m.match([1, 3])) == {(s0, 1), (s0, 3), (s2, 3)}
    assert len(m.match([99])) == 0
    assert m.watchers(2) == 2
    m.remove(s1)
    assert pairs_set(m.match([2])) == {(s0, 2)}
    # Slot recycling: the freed slot is reused.
    s3 = m.add([2])
    assert s3 == s1
    assert pairs_set(m.match([2])) == {(s0, 2), (s3, 2)}


def test_remove_is_idempotent_and_unsubscribes_all_rows():
    m = SubscriptionMatcher(use_device=False)
    s0 = m.add([4, 5])
    m.add([5])
    m.remove(s0)
    m.remove(s0)
    assert pairs_set(m.match([4, 5])) == {(1, 5)}
    assert m.watchers(4) == 0


def test_device_matches_host_mirror():
    """The device path (CSR arrays + masked gather) returns exactly the
    mirror's pairs, across fresh placement, incremental scatters, and
    an overflow-forced repack."""
    dm = SubscriptionMatcher()
    hm = SubscriptionMatcher(use_device=False)
    for i in range(12):
        rids = [i % 5, 5 + (i % 3)]  # every rid in 0..7 populated
        assert dm.add(rids) == hm.add(rids)
    changed = [1, 6, 9]  # 9: absent rid
    got = pairs_set(dm.match(changed))
    assert got == pairs_set(hm.match(changed))
    assert got == expected(hm, changed)
    rebuilds_before = dm.rebuilds
    # Churn WITHIN extent headroom: incremental scatters, no repack.
    for slot in (2, 5):
        dm.remove(slot)
        hm.remove(slot)
    s = dm.add([1, 6])
    assert s == hm.add([1, 6])
    got = pairs_set(dm.match(changed))
    assert got == pairs_set(hm.match(changed))
    assert got == expected(hm, changed)
    assert dm.rebuilds == rebuilds_before, "headroom churn repacked"
    assert dm.scatters >= 1, "no incremental scatter happened"
    # Overflow one row's extent: forces a repack, results unchanged.
    for _ in range(20):
        assert dm.add([6]) == hm.add([6])
    got = pairs_set(dm.match([6]))
    assert got == pairs_set(hm.match([6]))
    assert dm.rebuilds > rebuilds_before
    assert len(got) == dm.watchers(6)


def test_match_returns_exact_pairs_no_padding():
    m = SubscriptionMatcher()
    for i in range(5):
        m.add([100 + i])
    pairs = m.match([100, 103])
    assert pairs.shape == (2, 2)
    assert pairs_set(pairs) == {(0, 100), (3, 103)}
    # Quiet match: zero pairs, zero device work (host-known M == 0).
    assert m.match([999]).shape == (0, 2)


def test_match_phase_laps_recorded():
    """The "match" PHASES entry laps on the device path (and staging
    rides the engine's staging vocabulary)."""
    m = SubscriptionMatcher()
    m.add([1])
    m.add([1])
    assert len(m.match([1])) == 2
    assert m.phase_s["match"] > 0.0
    if m.status()["device"]:
        assert m.phase_s["download"] > 0.0


def test_status_shape():
    m = SubscriptionMatcher(use_device=False)
    m.add([1, 2])
    m.match([1])
    st = m.status()
    assert st["slots"] == 1 and st["rows"] == 2
    assert st["matched_total"] == 1
    assert st["device"] is False


@pytest.mark.parametrize("n", [1, 64, 257])
def test_scales_across_bucket_boundaries(n):
    """Bucketed shapes (changed-set pad, match cap, packed size) stay
    correct across their boundaries."""
    m = SubscriptionMatcher()
    for i in range(n):
        m.add([i % 7])
    changed = list(range(7))
    pairs = m.match(changed)
    assert len(pairs) == n
    assert pairs_set(pairs) == expected(m, changed)
