"""TLS end-to-end: server with --tls-cert/--tls-key, client pinning the
root via tls_ca (capability parity with reference doorman_server.go
TLS flags + client dial options)."""

import asyncio
import shutil
import subprocess

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.client import Client
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""

needs_openssl = pytest.mark.skipif(
    shutil.which("openssl") is None, reason="openssl not available"
)


@pytest.fixture
def certs(tmp_path):
    key, cert = tmp_path / "key.pem", tmp_path / "cert.pem"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", str(key), "-out", str(cert),
            "-days", "1", "-nodes",
            "-subj", "/CN=localhost",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True,
        capture_output=True,
    )
    return str(cert), str(key)


@needs_openssl
def test_tls_end_to_end(certs):
    cert, key = certs

    async def body():
        server = CapacityServer(
            "tls-server", TrivialElection(), minimum_refresh_interval=0.0
        )
        port = await server.start(
            0, host="127.0.0.1", tls_cert=cert, tls_key=key
        )
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        server.current_master = ""  # no redirects in this test

        client = await Client.connect(
            f"localhost:{port}", "tls-client",
            minimum_refresh_interval=0.0, tls_ca=cert,
        )
        res = await client.resource("r0", wants=25)
        got = await asyncio.wait_for(res.capacity().get(), timeout=10)
        assert got == 25.0
        await client.close()

        # A plaintext client against the TLS port must fail, not hang
        # forever: bounded retries surface the handshake error.
        plain = await Client.connect(
            f"localhost:{port}", "plain-client", minimum_refresh_interval=0.0
        )
        plain.conn.max_retries = 1
        res2 = await plain.resource("r0", wants=5)
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(res2.capacity().get(), timeout=2)
        await plain.close()

        await server.stop()

    asyncio.run(body())


def test_tls_requires_both_cert_and_key():
    async def body():
        server = CapacityServer("s", TrivialElection())
        with pytest.raises(ValueError):
            await server.start(0, host="127.0.0.1", tls_cert="/nope.pem")

    asyncio.run(body())
