"""TLS end-to-end: server with --tls-cert/--tls-key, client pinning the
root via tls_ca (capability parity with reference doorman_server.go
TLS flags + client dial options)."""

import asyncio
import shutil
import subprocess

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.client import Client
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

CONFIG = """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""

needs_openssl = pytest.mark.skipif(
    shutil.which("openssl") is None, reason="openssl not available"
)


@pytest.fixture
def certs(tmp_path):
    key, cert = tmp_path / "key.pem", tmp_path / "cert.pem"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", str(key), "-out", str(cert),
            "-days", "1", "-nodes",
            "-subj", "/CN=localhost",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True,
        capture_output=True,
    )
    return str(cert), str(key)


@needs_openssl
def test_tls_end_to_end(certs):
    cert, key = certs

    async def body():
        server = CapacityServer(
            "tls-server", TrivialElection(), minimum_refresh_interval=0.0
        )
        port = await server.start(
            0, host="127.0.0.1", tls_cert=cert, tls_key=key
        )
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        server.current_master = ""  # no redirects in this test

        client = await Client.connect(
            f"localhost:{port}", "tls-client",
            minimum_refresh_interval=0.0, tls_ca=cert,
        )
        res = await client.resource("r0", wants=25)
        got = await asyncio.wait_for(res.capacity().get(), timeout=10)
        assert got == 25.0
        await client.close()

        # A plaintext client against the TLS port must fail, not hang
        # forever: bounded retries surface the handshake error.
        plain = await Client.connect(
            f"localhost:{port}", "plain-client", minimum_refresh_interval=0.0
        )
        plain.conn.max_retries = 1
        res2 = await plain.resource("r0", wants=5)
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(res2.capacity().get(), timeout=2)
        await plain.close()

        await server.stop()

    asyncio.run(body())


@needs_openssl
def test_frontend_workers_terminate_tls(certs):
    """TLS terminates at the spawned listener workers: a secure
    channel pinning the self-signed root completes the loopback
    handshake and gets a grant through the worker's unary forward
    (the backend hop stays plaintext by design), while a plaintext
    client against the same port fails instead of hanging."""
    import socket
    import time

    import grpc

    from doorman_tpu.proto import doorman_pb2 as pb
    from doorman_tpu.proto.grpc_api import CapacityStub

    cert, key = certs

    async def body():
        server = CapacityServer(
            "tls-frontend", TrivialElection(), mode="immediate",
            tick_interval=0.2, minimum_refresh_interval=0.0,
            stream_push=True, stream_shards=2,
        )
        pool = server.attach_frontend(
            1, ring_bytes=1 << 18, inline=False,
            tls_cert=cert, tls_key=key,
        )
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            public_port = s.getsockname()[1]
        public_addr = f"127.0.0.1:{public_port}"
        try:
            backend_port = await server.start(0, host="127.0.0.1")
            await server.load_config(parse_yaml_config(CONFIG))
            await asyncio.sleep(0)
            server.current_master = public_addr
            await pool.start(public_addr, f"127.0.0.1:{backend_port}")

            # The spawned worker takes a moment to import grpc and
            # bind; ready means it has heartbeat the control surface.
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if pool.control.status()["worker_held"]:
                    break
                await asyncio.sleep(0.2)
            else:
                raise TimeoutError("worker never became ready")

            with open(cert, "rb") as f:
                root = f.read()
            creds = grpc.ssl_channel_credentials(root_certificates=root)
            async with grpc.aio.secure_channel(
                f"localhost:{public_port}", creds
            ) as ch:
                stub = CapacityStub(ch)
                req = pb.GetCapacityRequest(client_id="tls-fe-client")
                rr = req.resource.add()
                rr.resource_id = "r0"
                rr.wants = 25.0
                rr.priority = 1
                resp = await asyncio.wait_for(
                    stub.GetCapacity(req), timeout=30
                )
                assert resp.response[0].gets.capacity == 25.0

            # Plaintext against the TLS port: loud handshake failure,
            # not a hang.
            async with grpc.aio.insecure_channel(public_addr) as ch:
                stub = CapacityStub(ch)
                with pytest.raises(
                    (grpc.aio.AioRpcError, asyncio.TimeoutError)
                ):
                    await asyncio.wait_for(
                        stub.GetCapacity(req), timeout=5
                    )
        finally:
            await pool.stop()
            await server.stop()

    asyncio.run(body())


def test_tls_requires_both_cert_and_key():
    async def body():
        server = CapacityServer("s", TrivialElection())
        with pytest.raises(ValueError):
            await server.start(0, host="127.0.0.1", tls_cert="/nope.pem")

    asyncio.run(body())
