"""f32 parity characterization: the TPU-native f32 solve vs the f64
numpy oracles, all lanes, bench-representative scale, swept magnitudes.

BASELINE.md's parity ladder (backing reference
simulation/algo_proportional.py:31-65):
  * f64 solve = bit-identical to the oracles (tests/test_tick_oracles.py,
    tests/test_algorithms.py);
  * f32 solve (the dtype every TPU BENCH number uses) = within
    F32_REL_BOUND of the oracle, relative to the row's grant scale
    (max(capacity, max wants)), for every algorithm lane across demand
    magnitudes 1e-2..1e6.

Measured error tops out around 9e-8 (f32 eps territory — the lanes are
short reduction chains, so error stays near ulp); the bound pins 10x
headroom. If a solver change regresses past it, this test fails and
BASELINE.md's claim must be re-characterized, not widened silently.
"""

import numpy as np
import jax.numpy as jnp

import tests.conftest  # noqa: F401

from doorman_tpu.algorithms.kinds import AlgoKind
from doorman_tpu.algorithms.tick import F32_PARITY_REL_BOUND, oracle_row
from doorman_tpu.solver.dense import DenseBatch, solve_dense

# The documented f32 bound: max |gets_f32 - oracle_f64| per row,
# relative to max(capacity, max wants) of that row. ONE constant shared
# with bench.py's on-chip pallas gate (algorithms.tick owns it).
F32_REL_BOUND = F32_PARITY_REL_BOUND

R, K = 1024, 128  # 1024 resources x up to 128 clients per solve
SCALES = (1e-2, 1.0, 1e3, 1e6)
LANES = (
    AlgoKind.NO_ALGORITHM,
    AlgoKind.STATIC,
    AlgoKind.PROPORTIONAL_SHARE,
    AlgoKind.PROPORTIONAL_TOPUP,
    AlgoKind.FAIR_SHARE,
)


def _world(rng, scale):
    n = rng.integers(1, K, R)
    act = np.arange(K)[None, :] < n[:, None]
    wants = rng.random((R, K)) * scale * act
    has = rng.random((R, K)) * scale * 0.5 * act
    sub = rng.integers(1, 5, (R, K)) * act
    cap = rng.random(R) * scale * 50 + scale
    statc = rng.random(R) * scale
    return act, wants, has, sub, cap, statc


def _solve_f32(kind, act, wants, has, sub, cap, statc, learning=False):
    batch = DenseBatch(
        wants=jnp.asarray(wants, jnp.float32),
        has=jnp.asarray(has, jnp.float32),
        subclients=jnp.asarray(sub, jnp.float32),
        active=jnp.asarray(act),
        capacity=jnp.asarray(cap, jnp.float32),
        algo_kind=jnp.full(R, int(kind), jnp.int32),
        learning=jnp.full(R, learning),
        static_capacity=jnp.asarray(statc, jnp.float32),
    )
    return np.asarray(solve_dense(batch), np.float64)


def test_f32_error_bounded_across_lanes_and_magnitudes():
    worst = 0.0
    for scale in SCALES:
        rng = np.random.default_rng(int(np.log10(scale) * 7 + 29))
        act, wants, has, sub, cap, statc = _world(rng, scale)
        for kind in LANES:
            g32 = _solve_f32(kind, act, wants, has, sub, cap, statc)
            # Every 29th row against the f64 oracle (a full scan is
            # 5x4x1024 oracle evaluations; the sample keeps CI fast
            # while covering each lane at each magnitude 35+ times).
            for r in range(0, R, 29):
                m = act[r]
                w, h = wants[r, m], has[r, m]
                s = sub[r, m].astype(np.float64)
                expected = oracle_row(
                    int(kind), float(cap[r]), float(statc[r]), w, h, s
                )
                row_scale = max(
                    float(cap[r]), float(w.max()) if len(w) else 0.0, 1e-30
                )
                err = float(np.abs(g32[r, m] - expected).max()) / row_scale
                worst = max(worst, err)
                assert err <= F32_REL_BOUND, (
                    f"lane {kind} scale {scale:g} row {r}: f32 error "
                    f"{err:.3g} exceeds the documented bound "
                    f"{F32_REL_BOUND:g}"
                )
            # Feasibility must survive f32: the delivered table is what
            # the store (and every client) sees.
            feasible = kind in (
                AlgoKind.PROPORTIONAL_SHARE,
                AlgoKind.PROPORTIONAL_TOPUP,
                AlgoKind.FAIR_SHARE,
            )
            if feasible:
                sums = (g32 * act).sum(axis=1)
                assert (
                    sums <= cap * (1 + F32_REL_BOUND) + 1e-12
                ).all(), f"lane {kind} scale {scale:g} oversubscribed"
    # The bound must stay a bound, not an equality — if this starts
    # failing the solve got *better*; tighten F32_REL_BOUND instead.
    assert worst < F32_REL_BOUND


def test_f32_learning_replays_has_exactly():
    """The learning lane is a passthrough: f32 replays the f32-rounded
    has bit-for-bit (error ≤ eps from the cast alone)."""
    rng = np.random.default_rng(5)
    act, wants, has, sub, cap, statc = _world(rng, 1e3)
    g32 = _solve_f32(
        AlgoKind.PROPORTIONAL_SHARE, act, wants, has, sub, cap, statc,
        learning=True,
    )
    np.testing.assert_array_equal(
        g32 * act, has.astype(np.float32).astype(np.float64) * act
    )


def test_f32_bound_holds_at_max_bucket_width():
    """The characterization above runs at K=128; reduction chains grow
    with bucket width, so also pin the bound at the dense cap
    (DENSE_MAX_K=4096-wide rows). Measured error stays ~1e-10 relative
    — far inside the documented bound."""
    from doorman_tpu.solver.batch import DENSE_MAX_K

    Rw, Kw = 8, DENSE_MAX_K
    rng = np.random.default_rng(17)
    n = rng.integers(Kw // 2, Kw, Rw)
    act = np.arange(Kw)[None, :] < n[:, None]
    wants = rng.random((Rw, Kw)) * 1e3 * act
    has = rng.random((Rw, Kw)) * 500 * act
    sub = rng.integers(1, 5, (Rw, Kw)) * act
    cap = rng.random(Rw) * 2_000_000 + 1e3
    statc = rng.random(Rw) * 100
    for kind in (
        AlgoKind.PROPORTIONAL_SHARE,
        AlgoKind.FAIR_SHARE,
        AlgoKind.PROPORTIONAL_TOPUP,
    ):
        batch = DenseBatch(
            wants=jnp.asarray(wants, jnp.float32),
            has=jnp.asarray(has, jnp.float32),
            subclients=jnp.asarray(sub, jnp.float32),
            active=jnp.asarray(act),
            capacity=jnp.asarray(cap, jnp.float32),
            algo_kind=jnp.full(Rw, int(kind), jnp.int32),
            learning=jnp.zeros(Rw, bool),
            static_capacity=jnp.asarray(statc, jnp.float32),
        )
        g32 = np.asarray(solve_dense(batch), np.float64)
        for r in range(Rw):
            m = act[r]
            expected = oracle_row(
                int(kind), float(cap[r]), float(statc[r]),
                wants[r, m], has[r, m], sub[r, m].astype(np.float64),
            )
            row_scale = max(float(cap[r]), float(wants[r, m].max()))
            err = float(np.abs(g32[r, m] - expected).max()) / row_scale
            assert err <= F32_REL_BOUND, (
                f"lane {kind} row {r} at K={Kw}: {err:.3g}"
            )
