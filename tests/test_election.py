"""Election tests: the TTL-lock state machine (reference election.go:89-172)
driven over the in-memory KV with fault injection, and server failover
behavior (state wipe + learning mode on re-election)."""

import asyncio

import tests.conftest  # noqa: F401

from doorman_tpu.server.election import (
    InMemoryKV,
    KVElection,
    TrivialElection,
)


def run(coro):
    return asyncio.run(coro)


class Recorder:
    def __init__(self):
        self.is_master_events = []
        self.current_events = []
        self.master_changed = asyncio.Event()
        self.current_changed = asyncio.Event()

    async def on_is_master(self, is_master):
        self.is_master_events.append(is_master)
        self.master_changed.set()

    async def on_current(self, current):
        self.current_events.append(current)
        self.current_changed.set()

    async def wait_master_change(self, timeout=5):
        await asyncio.wait_for(self.master_changed.wait(), timeout)
        self.master_changed.clear()


def test_trivial_election_wins_immediately():
    async def body():
        rec = Recorder()
        await TrivialElection().run("me", rec.on_is_master, rec.on_current)
        assert rec.is_master_events == [True]
        assert rec.current_events == ["me"]

    run(body())


def test_kv_election_single_candidate_wins():
    async def body():
        kv = InMemoryKV()
        election = KVElection(kv, "/lock", ttl=0.3)
        rec = Recorder()
        await election.run("a", rec.on_is_master, rec.on_current)
        await rec.wait_master_change()
        assert rec.is_master_events == [True]
        assert await kv.get("/lock") == "a"
        await election.stop()

    run(body())


def test_kv_election_second_candidate_loses():
    async def body():
        kv = InMemoryKV()
        e1 = KVElection(kv, "/lock", ttl=0.5)
        e2 = KVElection(kv, "/lock", ttl=0.5)
        r1, r2 = Recorder(), Recorder()
        await e1.run("a", r1.on_is_master, r1.on_current)
        await r1.wait_master_change()
        await e2.run("b", r2.on_is_master, r2.on_current)
        await asyncio.sleep(0.3)
        assert r2.is_master_events == []  # b never wins while a renews
        assert await kv.get("/lock") == "a"
        await e1.stop()
        await e2.stop()

    run(body())


def test_kv_election_failover_on_expiry():
    async def body():
        kv = InMemoryKV()
        e1 = KVElection(kv, "/lock", ttl=0.3)
        r1 = Recorder()
        await e1.run("a", r1.on_is_master, r1.on_current)
        await r1.wait_master_change()
        assert r1.is_master_events == [True]

        # Fault injection: the lock vanishes (as if etcd expired it) and a
        # rival takes it; a's next renewal fails => mastership lost.
        kv.expire("/lock")
        assert await kv.acquire("/lock", "b", 10.0)
        await r1.wait_master_change()
        assert r1.is_master_events == [True, False]
        await e1.stop()

    run(body())


def test_server_failover_wipes_state_and_relearns():
    async def body():
        from doorman_tpu.proto import doorman_pb2 as pb
        from doorman_tpu.server.config import parse_yaml_config
        from doorman_tpu.server.server import CapacityServer

        server = CapacityServer("s1", TrivialElection())
        await server.load_config(
            parse_yaml_config(
                """
resources:
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60, refresh_interval: 1}
"""
            )
        )
        await server._on_is_master(True)
        res = server.get_or_create_resource("r")
        res.store.assign("c1", 60, 1, 10.0, 10.0, 1)
        assert server.resources

        # Losing mastership wipes all lease state (server.go:438-455).
        await server._on_is_master(False)
        assert server.resources == {}
        assert not server.is_master

        # Winning again restarts learning mode from the new
        # became_master_at.
        await server._on_is_master(True)
        res = server.get_or_create_resource("r")
        assert res.in_learning_mode

    run(body())
