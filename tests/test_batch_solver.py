"""BatchSolver integration: stores -> snapshot -> device solve -> write-back,
and equivalence with the per-request path at the protocol's fixed points."""

import numpy as np

import tests.conftest  # noqa: F401

from doorman_tpu.algorithms import Request
from doorman_tpu.core.resource import Resource
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.solver.batch import BatchSolver


class FakeClock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def template(kind=pb.Algorithm.PROPORTIONAL_SHARE, capacity=120.0,
             lease=60, refresh=16, glob="*"):
    return pb.ResourceTemplate(
        identifier_glob=glob,
        capacity=capacity,
        algorithm=pb.Algorithm(
            kind=kind, lease_length=lease, refresh_interval=refresh
        ),
    )


def test_tick_solves_and_writes_back():
    clock = FakeClock()
    res = Resource("r0", template(), clock=clock)
    # Three clients report wants; initial grants via immediate path.
    for c, w in [("a", 60.0), ("b", 60.0), ("c", 10.0)]:
        res.store.assign(c, 60, 16, 0.0, w, 1)

    solver = BatchSolver(clock=clock)
    grants = solver.tick([res])

    # Overload: proportional scaling 120/130, clamped by free capacity.
    g = grants["r0"]
    assert abs(sum(g.values()) - 120.0) < 1e-9 or sum(g.values()) <= 120.0
    np.testing.assert_allclose(
        [g["a"], g["b"], g["c"]],
        np.array([60.0, 60.0, 10.0]) * (120.0 / 130.0),
    )
    # Write-back updated the store and stamped fresh expiries.
    assert res.store.get("a").has == g["a"]
    assert res.store.get("a").expiry == clock() + 60


def test_tick_is_fixed_point_of_immediate_path():
    # After a batched tick, running the scalar per-request algorithm for any
    # single client must not change its grant (steady state equivalence).
    clock = FakeClock()
    res = Resource("r0", template(), clock=clock)
    rng = np.random.default_rng(0)
    wants = rng.integers(1, 100, 20).astype(float)
    for i, w in enumerate(wants):
        res.store.assign(f"c{i}", 60, 16, 0.0, float(w), 1)

    solver = BatchSolver(clock=clock)
    solver.tick([res])
    solver.tick([res])  # second tick: free capacity now reflects grants

    before = {c: res.store.get(c).has for c in [f"c{i}" for i in range(20)]}
    for i in range(20):
        c = f"c{i}"
        lease = res.decide(Request(c, before[c], float(wants[i]), 1))
        assert abs(lease.has - before[c]) < 1e-6, (c, lease.has, before[c])


def test_learning_mode_replays_has():
    clock = FakeClock()
    res = Resource(
        "r0", template(), learning_mode_end=clock() + 100, clock=clock
    )
    res.store.assign("a", 60, 16, 33.0, 50.0, 1)
    solver = BatchSolver(clock=clock)
    grants = solver.tick([res])
    assert grants["r0"]["a"] == 33.0


def test_expired_leases_swept_before_solve():
    clock = FakeClock()
    res = Resource("r0", template(), clock=clock)
    res.store.assign("old", 5, 1, 10.0, 10.0, 1)
    clock.advance(10)
    res.store.assign("new", 60, 16, 0.0, 10.0, 1)
    solver = BatchSolver(clock=clock)
    grants = solver.tick([res])
    assert "old" not in grants["r0"]
    assert grants["r0"]["new"] == 10.0


def test_multiple_resources_mixed_kinds():
    clock = FakeClock()
    r_prop = Resource("prop", template(), clock=clock)
    r_fair = Resource(
        "fair", template(kind=pb.Algorithm.FAIR_SHARE), clock=clock
    )
    r_none = Resource(
        "none", template(kind=pb.Algorithm.NO_ALGORITHM), clock=clock
    )
    for r in (r_prop, r_fair, r_none):
        for c, w in [("a", 100.0), ("b", 40.0)]:
            r.store.assign(c, 60, 16, 0.0, w, 1)
    solver = BatchSolver(clock=clock)
    grants = solver.tick([r_prop, r_fair, r_none])
    # none: everyone gets wants
    assert grants["none"] == {"a": 100.0, "b": 40.0}
    # fair: waterfill of 120 => a gets 80, b gets 40
    assert grants["fair"] == {"a": 80.0, "b": 40.0}
    # prop: scaled by 120/140
    np.testing.assert_allclose(
        [grants["prop"]["a"], grants["prop"]["b"]],
        [100.0 * 120.0 / 140.0, 40.0 * 120.0 / 140.0],
    )


def test_release_between_snapshot_and_apply_stays_released():
    # A client released while the device solve is in flight must not be
    # resurrected by the grant write-back.
    clock = FakeClock()
    res = Resource("r0", template(), clock=clock)
    for c, w in [("a", 60.0), ("b", 60.0)]:
        res.store.assign(c, 60, 16, 0.0, w, 1)
    solver = BatchSolver(clock=clock)
    snap = solver.prepare([res])
    gets = solver.solve(snap)
    res.release("a")  # concurrent ReleaseCapacity
    grants = solver.apply([res], snap, gets)
    assert "a" not in grants["r0"]
    assert not res.store.has_client("a")
    assert res.store.has_client("b")


def test_wants_update_mid_solve_is_preserved():
    clock = FakeClock()
    res = Resource("r0", template(), clock=clock)
    res.store.assign("a", 60, 16, 0.0, 50.0, 1)
    solver = BatchSolver(clock=clock)
    snap = solver.prepare([res])
    gets = solver.solve(snap)
    # Demand changes while the solve is in flight.
    res.store.assign("a", 60, 16, res.store.get("a").has, 99.0, 1)
    solver.apply([res], snap, gets)
    assert res.store.get("a").wants == 99.0  # not clobbered by write-back


def test_parent_expiry_zeroes_capacity():
    clock = FakeClock()
    res = Resource("r0", template(), clock=clock)
    res.load_config(template(), parent_expiry=clock() - 1)
    res.store.assign("a", 60, 16, 0.0, 50.0, 1)
    solver = BatchSolver(clock=clock)
    grants = solver.tick([res])
    assert grants["r0"]["a"] == 0.0
