"""Shadow-oracle audit (obs/audit.py): the fixpoint property across
every scalar lane, the two-strike confirmation rule, the iterative-lane
ulp bound, the skip set, and the live server's divergence blast
(counter + flight-recorder dump + failing SLO gate).
"""

import asyncio

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.algorithms.kinds import AlgoKind
from doorman_tpu.algorithms.tick import oracle_row
from doorman_tpu.core.resource import Resource
from doorman_tpu.obs.audit import (
    ITERATIVE_LANES,
    ITERATIVE_REL_BOUND,
    ShadowAuditor,
)
from doorman_tpu.proto import doorman_pb2 as pb

# (kind, capacity, static, wants, sub) per scalar lane — overloaded so
# grants actually bind, subclients non-uniform so weighted lanes weight.
LANES = [
    (AlgoKind.NO_ALGORITHM, 100.0, 0.0),
    (AlgoKind.STATIC, 100.0, 12.5),
    (AlgoKind.PROPORTIONAL_SHARE, 100.0, 0.0),
    (AlgoKind.PROPORTIONAL_TOPUP, 100.0, 0.0),
    (AlgoKind.FAIR_SHARE, 100.0, 0.0),
    (AlgoKind.MAX_MIN_FAIR, 100.0, 0.0),
    (AlgoKind.BALANCED_FAIRNESS, 100.0, 0.0),
    (AlgoKind.PROPORTIONAL_FAIRNESS, 100.0, 0.0),
]
WANTS = np.array([20.0, 30.0, 60.0, 45.0], np.float64)
SUB = np.array([1.0, 2.0, 1.0, 3.0], np.float64)


def converged_entry(kind, capacity=100.0, static=0.0, *, iters=500):
    """Iterate the oracle to its fixpoint: the delivered steady state a
    healthy server's store holds between wants changes."""
    has = np.zeros_like(WANTS)
    for _ in range(iters):
        nxt = oracle_row(int(kind), capacity, static, WANTS, has, SUB)
        if np.array_equal(nxt, has):
            break
        has = nxt
    return {
        "rid": f"r-{int(kind)}",
        "tick": 0,
        "kind": int(kind),
        "capacity": float(capacity),
        "static": float(static),
        "clients": [f"c{i}" for i in range(len(WANTS))],
        "has": has.copy(),
        "wants": WANTS.copy(),
        "sub": SUB.copy(),
    }


def mk_auditor(**kw):
    kw.setdefault("inline", True)
    kw.setdefault("clock", lambda: 0.0)
    return ShadowAuditor(sample=kw.pop("sample", 4), **kw)


# ---------------------------------------------------------------------
# sampling predicate
# ---------------------------------------------------------------------


def test_should_sample_period_and_transition():
    aud = mk_auditor(sample=4)
    assert aud.should_sample(0, "scoped")  # tick % 4 == 0
    assert not aud.should_sample(1, "scoped")
    assert aud.should_sample(2, "full")  # solve-mode transition
    assert not aud.should_sample(3, "full")
    assert aud.should_sample(4, "full")


def test_sample_interval_must_be_positive():
    with pytest.raises(ValueError):
        ShadowAuditor(sample=0)


# ---------------------------------------------------------------------
# the fixpoint property, lane by lane
# ---------------------------------------------------------------------


@pytest.mark.parametrize("kind,capacity,static", LANES,
                         ids=lambda v: getattr(v, "name", None))
def test_fixpoint_is_clean_at_convergence(kind, capacity, static):
    """At a converged row the audit comparison is silent — even across
    two samples with identical digests (the two-strike rule never gets
    strike one)."""
    aud = mk_auditor()
    entry = converged_entry(kind, capacity, static)
    aud._compare([entry])
    aud._compare([entry])
    assert aud.divergences == 0 and aud.details == []


def test_two_strike_flags_stable_corruption_once():
    aud = mk_auditor()
    entry = converged_entry(AlgoKind.FAIR_SHARE)
    entry["has"][0] *= 0.75  # a silently-scaled grant, digest-stable
    aud._compare([entry])  # strike one: pending, not flagged
    assert aud.divergences == 0
    aud._compare([entry])  # identical digest -> confirmed
    assert aud.divergences == 1
    aud._compare([entry])  # already flagged: counted once
    aud._compare([entry])
    assert aud.divergences == 1
    (detail,) = aud.details
    assert detail["rid"] == entry["rid"] and detail["rows"] == [0]
    assert detail["has"][0] == pytest.approx(detail["expected"][0] * 0.75)


def test_moving_inputs_never_flag():
    """A converging or delivery-lagged row changes `has` between
    samples, so its digest moves — strike one never becomes two."""
    aud = mk_auditor()
    base = converged_entry(AlgoKind.FAIR_SHARE)
    for i in range(1, 6):
        entry = dict(base)
        entry["has"] = base["has"] * (1.0 - 0.01 * i)  # still wrong...
        aud._compare([entry])  # ...but differently wrong each sample
    assert aud.divergences == 0


def test_clean_sample_resets_the_strike():
    aud = mk_auditor()
    good = converged_entry(AlgoKind.FAIR_SHARE)
    bad = dict(good)
    bad["has"] = good["has"].copy()
    bad["has"][1] *= 0.5
    aud._compare([bad])  # strike one
    aud._compare([good])  # healed: pending cleared
    aud._compare([bad])  # strike one again, not confirmation
    assert aud.divergences == 0
    aud._compare([bad])
    assert aud.divergences == 1


def test_iterative_lane_gets_ulp_slack():
    kind = AlgoKind.MAX_MIN_FAIR
    assert kind in ITERATIVE_LANES
    aud = mk_auditor()
    entry = converged_entry(kind)
    # One-ulp reassociation noise: inside the bound, never flagged.
    entry["has"] = entry["has"] * (1.0 + np.finfo(np.float64).eps)
    aud._compare([entry])
    aud._compare([entry])
    assert aud.divergences == 0
    # A real divergence dwarfs the bound and is still caught.
    entry2 = converged_entry(kind)
    entry2["has"][0] *= 1.0 + 1e6 * ITERATIVE_REL_BOUND
    aud._compare([entry2])
    aud._compare([entry2])
    assert aud.divergences == 1


def test_exact_lanes_flag_single_bit_drift():
    aud = mk_auditor()
    entry = converged_entry(AlgoKind.FAIR_SHARE)
    entry["has"][2] = np.nextafter(entry["has"][2], np.inf)
    aud._compare([entry])
    aud._compare([entry])
    assert aud.divergences == 1


# ---------------------------------------------------------------------
# snapshot: what gets audited
# ---------------------------------------------------------------------


def _template(kind, capacity=100.0, variant=None):
    algo = pb.Algorithm(kind=kind, lease_length=60, refresh_interval=1)
    if variant:
        p = algo.parameters.add()
        p.name = "variant"
        p.value = variant
    return pb.ResourceTemplate(
        identifier_glob="*", capacity=capacity, algorithm=algo
    )


def test_snapshot_skips_learning_empty_and_bandless_lanes():
    clock = lambda: 1000.0  # noqa: E731
    audited = Resource(
        "r-live", _template(pb.Algorithm.FAIR_SHARE), clock=clock
    )
    audited.store.assign("c0", 60, 1, 0.0, 40.0, 1)
    learning = Resource(
        "r-learning", _template(pb.Algorithm.FAIR_SHARE),
        learning_mode_end=2000.0, clock=clock,
    )
    learning.store.assign("c0", 60, 1, 0.0, 40.0, 1)
    empty = Resource(
        "r-empty", _template(pb.Algorithm.FAIR_SHARE), clock=clock
    )
    bands = Resource(
        "r-bands", _template(pb.Algorithm.PRIORITY_BANDS), clock=clock
    )
    bands.store.assign("c0", 60, 1, 0.0, 40.0, 1)
    aud = mk_auditor()
    snap = aud.snapshot(
        {
            "r-live": audited,
            "r-learning": learning,
            "r-empty": empty,
            "r-bands": bands,
        },
        tick=7,
    )
    assert [e["rid"] for e in snap] == ["r-live"]
    assert snap[0]["kind"] == int(AlgoKind.FAIR_SHARE)
    assert snap[0]["tick"] == 7
    assert snap[0]["wants"].tolist() == [40.0]


def test_snapshot_resolves_variant_lanes():
    clock = lambda: 1000.0  # noqa: E731
    res = Resource(
        "r-maxmin",
        _template(pb.Algorithm.FAIR_SHARE, variant="maxmin"),
        clock=clock,
    )
    res.store.assign("c0", 60, 1, 0.0, 40.0, 1)
    aud = mk_auditor()
    (entry,) = aud.snapshot({"r-maxmin": res}, tick=0)
    assert entry["kind"] == int(AlgoKind.MAX_MIN_FAIR)


# ---------------------------------------------------------------------
# executor path
# ---------------------------------------------------------------------


def test_executor_path_matches_inline():
    hits = []
    aud = ShadowAuditor(
        sample=1, inline=False, on_divergence=hits.append,
        clock=lambda: 0.0,
    )
    entry = converged_entry(AlgoKind.FAIR_SHARE)
    entry["has"][0] *= 0.75
    # Feed pre-built entries through _compare via the executor the way
    # maybe_sample does, then drain before asserting.
    aud._executor.submit(aud._compare_safe, [dict(entry, has=entry["has"].copy())])
    aud._executor.submit(aud._compare_safe, [dict(entry, has=entry["has"].copy())])
    aud.drain()
    assert aud.divergences == 1 and len(hits) == 1
    aud.close()
    assert aud.inline  # post-close comparisons run on the caller

    st = aud.status()
    assert st["divergences"] == 1 and len(st["details"]) == 1


def test_on_divergence_hook_failure_is_contained():
    def boom(detail):
        raise RuntimeError("hook crashed")

    aud = mk_auditor(on_divergence=boom)
    entry = converged_entry(AlgoKind.FAIR_SHARE)
    entry["has"][0] *= 0.75
    aud._compare([entry])
    aud._compare([entry])  # hook raises; the audit keeps counting
    assert aud.divergences == 1


# ---------------------------------------------------------------------
# the live server: clean eight-lane run, then the divergence blast
# ---------------------------------------------------------------------

EIGHT_LANE_CONFIG = """
resources:
- identifier_glob: "r-none"
  capacity: 100
  algorithm: {kind: NO_ALGORITHM, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
- identifier_glob: "r-static"
  capacity: 12.5
  algorithm: {kind: STATIC, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
- identifier_glob: "r-prop"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
- identifier_glob: "r-topup"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0,
              parameters: [{name: variant, value: topup}]}
- identifier_glob: "r-fair"
  capacity: 100
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
- identifier_glob: "r-maxmin"
  capacity: 100
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0,
              parameters: [{name: variant, value: maxmin}]}
- identifier_glob: "r-balanced"
  capacity: 100
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0,
              parameters: [{name: variant, value: balanced}]}
- identifier_glob: "r-logutil"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0,
              parameters: [{name: variant, value: logutil}]}
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
"""

RIDS = ["r-none", "r-static", "r-prop", "r-topup", "r-fair", "r-maxmin",
        "r-balanced", "r-logutil"]


async def _eight_lane_server(ticks):
    from doorman_tpu.client import Client
    from doorman_tpu.server.config import parse_yaml_config
    from doorman_tpu.server.election import TrivialElection
    from doorman_tpu.server.server import CapacityServer

    server = CapacityServer(
        "audit-server", TrivialElection(), mode="batch",
        minimum_refresh_interval=0.0, audit_sample=2, audit_inline=True,
    )
    port = await server.start(0, host="127.0.0.1")
    await server.load_config(parse_yaml_config(EIGHT_LANE_CONFIG))
    await asyncio.sleep(0)
    clients = []
    for i, wants in enumerate([20.0, 30.0, 60.0]):
        c = await Client.connect(
            f"127.0.0.1:{port}", f"c{i}", minimum_refresh_interval=0.0
        )
        for rid in RIDS:
            await c.resource(rid, wants=wants)
        clients.append(c)
    # Drive until `ticks` solves have APPLIED: the resident lane
    # pipelines dispatch, so the first tick_once stages without
    # landing and the audit hook (keyed on applied ticks) would
    # otherwise see one fewer aligned sample than the loop count.
    for _ in range(ticks + 4):
        if server._ticks_done >= ticks:
            break
        await server.tick_once()
        for c in clients:
            await c.refresh_once()
    assert server._ticks_done >= ticks
    return server, clients


async def _teardown(server, clients):
    for c in clients:
        await c.close()
    await server.stop()


def test_clean_run_all_eight_lanes_zero_divergences():
    async def body():
        server, clients = await _eight_lane_server(12)
        try:
            st = server.shadow_audit.status()
            assert st["samples"] >= 6
            # All eight lanes minus the skip set were actually compared.
            assert st["compared_resources"] >= 6 * len(RIDS)
            assert st["divergences"] == 0 and st["details"] == []
            verdicts = {v["slo"]: v for v in server.evaluate_slos()}
            assert verdicts["audit_divergence"]["status"] == "pass"
        finally:
            await _teardown(server, clients)

    asyncio.run(body())


def test_forced_corruption_fires_the_blast():
    """Silently scale one delivered grant: the auditor confirms within
    two samples and the blast lands — counter, flight-recorder dump,
    standing SLO failure."""
    from doorman_tpu.obs import metrics as metrics_mod

    async def body():
        server, clients = await _eight_lane_server(8)
        try:
            assert server.shadow_audit.divergences == 0
            store = server.resources["r-fair"].store
            store.regrant("c0", store.get("c0").has * 0.75)
            # Two aligned samples confirm (tick numbers divisible by K).
            aud = server.shadow_audit
            aud.maybe_sample(100, None, server.resources)
            aud.maybe_sample(102, None, server.resources)
            assert aud.divergences == 1
            (detail,) = aud.details
            assert detail["rid"] == "r-fair" and "c0" in detail["clients"]
            counter = metrics_mod.default_registry().counter(
                "doorman_audit_divergence", "", labels=("server", "resource")
            )
            assert counter.value("audit-server", "r-fair") == 1
            assert server.flightrec.last_dump is not None
            verdicts = {v["slo"]: v for v in server.evaluate_slos()}
            assert verdicts["audit_divergence"]["status"] == "fail"
        finally:
            await _teardown(server, clients)

    asyncio.run(body())
