"""doormanlint (tools/lint): every checker catches its known-bad
fixture — including the exact PR-4 pallas enum-closure pattern — known
good stays clean, and the suppression/baseline semantics hold.

Pure stdlib under test (no jax import): the fixtures are tiny source
trees written under tmp_path with the repo-relative layout the checkers
scope on, each carrying its own registries (RepoContext mines PHASES /
KNOWN_SPAN_NAMES / FUSED_TRACKED_WRITERS from the scanned tree itself).
The final test runs the full suite over the REAL repo and asserts the
acceptance criterion: zero unsuppressed, unbaselined findings.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tools.lint.core import (
    apply_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------
# fixture scaffolding
# ---------------------------------------------------------------------

KINDS = """
import enum


class AlgoKind(enum.IntEnum):
    NO_ALGORITHM = 0
    FAIR_SHARE = 3
    MAX_MIN_FAIR = 7
    BALANCED_FAIRNESS = 8
    PROPORTIONAL_FAIRNESS = 9
"""

ENGINE_REGISTRY = """
PHASES = (
    "sweep", "drain", "config", "pack", "staging", "upload", "solve",
    "download", "apply", "rebuild",
)
"""

TRACE_REGISTRY = """
KNOWN_SPAN_NAMES = frozenset({"server.tick", "server.*", "client.refresh"})
KNOWN_INSTANT_NAMES = frozenset({"election.transition", "shard.*"})
"""


class Tree:
    """A miniature repo tree the linter runs over."""

    def __init__(self, root: Path):
        self.root = root
        self.write("doorman_tpu/algorithms/kinds.py", KINDS)
        self.write("doorman_tpu/solver/engine.py", ENGINE_REGISTRY)
        self.write("doorman_tpu/obs/trace.py", TRACE_REGISTRY)

    def write(self, rel: str, text: str) -> None:
        p = self.root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text, encoding="utf-8")

    def run(self, rules=None):
        return run_lint(self.root, rules=rules)

    def active(self, rules=None):
        return [f for f in self.run(rules) if not f.suppressed]


@pytest.fixture()
def tree(tmp_path):
    return Tree(tmp_path)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------
# jit-closure-capture — the PR-4 regression class
# ---------------------------------------------------------------------

# The exact PR-4 pattern: solve_lanes' where-chain comparing a traced
# column against a bare IntEnum member inside a pallas kernel body
# (lanes.py pre-fix materialized AlgoKind.* as int64 closure consts).
PR4_BAD = """
import jax.numpy as jnp

from doorman_tpu.algorithms.kinds import AlgoKind


def _kernel(kind_ref, wants_ref, out_ref):
    gets = jnp.zeros_like(wants_ref[:])
    gets = jnp.where(kind_ref[:] == AlgoKind.FAIR_SHARE, wants_ref[:], gets)
    out_ref[:] = gets
"""

PR4_GOOD = PR4_BAD.replace("== AlgoKind.FAIR_SHARE", "== int(AlgoKind.FAIR_SHARE)")


def test_jit_capture_flags_pr4_enum_closure(tree):
    tree.write("doorman_tpu/solver/pallas_dense.py", PR4_BAD)
    found = tree.active(rules=["jit-closure-capture"])
    assert len(found) == 1
    assert "AlgoKind.FAIR_SHARE" in found[0].message
    assert "int(" in found[0].message


def test_jit_capture_int_wrap_is_clean(tree):
    tree.write("doorman_tpu/solver/pallas_dense.py", PR4_GOOD)
    assert tree.active(rules=["jit-closure-capture"]) == []


def test_jit_capture_flags_new_portfolio_members(tree):
    """The fairness-portfolio AlgoKind members are exactly the PR-4
    jit-closure-capture bug class: a NEW member used bare in device
    code must be flagged by the mined-registry rule (real lanes wrap
    with int()) — the registry is mined from the tree's IntEnum
    classes, so newly added members are covered without touching the
    linter."""
    tree.write("doorman_tpu/solver/pallas_dense.py", """
import jax.numpy as jnp

from doorman_tpu.algorithms.kinds import AlgoKind


def _kernel(kind_ref, wants_ref, out_ref):
    gets = jnp.zeros_like(wants_ref[:])
    gets = jnp.where(
        kind_ref[:] == AlgoKind.MAX_MIN_FAIR, wants_ref[:], gets
    )
    gets = jnp.where(
        kind_ref[:] == int(AlgoKind.BALANCED_FAIRNESS), wants_ref[:], gets
    )
    out_ref[:] = gets
""")
    found = tree.active(rules=["jit-closure-capture"])
    assert len(found) == 1
    assert "AlgoKind.MAX_MIN_FAIR" in found[0].message


def test_jit_capture_covers_jitted_functions(tree):
    tree.write("doorman_tpu/solver/lanes.py", """
import jax

from doorman_tpu.algorithms.kinds import AlgoKind


@jax.jit
def solve(kind):
    return kind == AlgoKind.NO_ALGORITHM
""")
    assert len(tree.active(rules=["jit-closure-capture"])) == 1


def test_jit_capture_ignores_host_code(tree):
    # Host-side template partitioning compares enums freely (no jnp use,
    # no jit, not a kernel).
    tree.write("doorman_tpu/solver/batch.py", """
from doorman_tpu.algorithms.kinds import AlgoKind


def partition(templates):
    return [t for t in templates if t.kind == AlgoKind.FAIR_SHARE]
""")
    assert tree.active(rules=["jit-closure-capture"]) == []


# ---------------------------------------------------------------------
# host-sync-in-hot-path
# ---------------------------------------------------------------------

HOT_BAD = """
def dispatch(self, resources, ph):
    out = self._tick_fn(resources)
    total = float(out)  # sync before the solve lap closes
    ph.lap("solve")
    return total
"""

HOT_GOOD = """
def collect(self, handle, ph):
    out = handle.dispatch()
    gets = out.sum()
    ph.lap("download")
    applied = float(gets)
    n = applied.item() if hasattr(applied, "item") else 0
    ph.lap("apply")
    return n
"""


def test_host_sync_flags_sync_outside_delivery(tree):
    tree.write("doorman_tpu/solver/resident.py", HOT_BAD)
    found = tree.active(rules=["host-sync-in-hot-path"])
    assert len(found) == 1
    assert "'solve'" in found[0].message


def test_host_sync_delivery_phases_are_exempt(tree):
    tree.write("doorman_tpu/solver/resident.py", HOT_GOOD)
    assert tree.active(rules=["host-sync-in-hot-path"]) == []


def test_host_sync_hard_syncs_need_no_device_provenance(tree):
    tree.write("doorman_tpu/solver/resident.py", """
def dispatch(self, table, ph):
    table.block_until_ready()
    ph.lap("upload")
""")
    found = tree.active(rules=["host-sync-in-hot-path"])
    assert len(found) == 1
    assert "block_until_ready" in found[0].message


def test_host_sync_ignores_unphased_helpers(tree):
    # No PhaseRecorder laps -> not part of the stage skeleton.
    tree.write("doorman_tpu/solver/util.py", """
def land(handle):
    return float(handle.out)
""")
    assert tree.active(rules=["host-sync-in-hot-path"]) == []


# ---------------------------------------------------------------------
# fused-writer-discipline
# ---------------------------------------------------------------------

SERVER_HDR = """
FUSED_TRACKED_WRITERS = frozenset({"CapacityServer._decide"})


class CapacityServer:
    def _fused_invalidate(self, resource_id=None):
        pass

"""


def test_fused_writer_flags_untracked_writer(tree):
    tree.write("doorman_tpu/server/server.py", SERVER_HDR + """
    def new_rpc_path(self, res):
        res.store.assign("client", 10.0, 5.0, 0.0, 1.0, 1)
""")
    found = tree.active(rules=["fused-writer-discipline"])
    assert len(found) == 1
    assert "new_rpc_path" in found[0].message
    assert "FUSED_TRACKED_WRITERS" in found[0].message


def test_fused_writer_invalidating_writer_is_clean(tree):
    tree.write("doorman_tpu/server/server.py", SERVER_HDR + """
    def release_path(self, res):
        res.release("client")
        self._fused_invalidate("r")
""")
    assert tree.active(rules=["fused-writer-discipline"]) == []


def test_fused_writer_registry_entry_is_clean(tree):
    tree.write("doorman_tpu/server/server.py", SERVER_HDR + """
    def _decide(self, res, request):
        return res.decide(request)
""")
    assert tree.active(rules=["fused-writer-discipline"]) == []


def test_fused_writer_out_of_scope_module_ignored(tree):
    tree.write("doorman_tpu/persist/restore.py", """
def rebuild(store):
    store.bulk_assign([])
""")
    assert tree.active(rules=["fused-writer-discipline"]) == []


# ---------------------------------------------------------------------
# seeded-determinism
# ---------------------------------------------------------------------


def test_determinism_flags_wall_clock_and_global_rng(tree):
    tree.write("doorman_tpu/chaos/bad.py", """
import random
import time


def jitter():
    return time.time() + random.random()
""")
    found = tree.active(rules=["seeded-determinism"])
    assert len(found) == 2
    assert {"time.time" in f.message or "random.random" in f.message
            for f in found} == {True}


def test_determinism_seam_default_arg_is_clean(tree):
    tree.write("doorman_tpu/server/timing.py", """
import random
import time
from typing import Callable, Optional


def schedule(clock: Callable[[], float] = time.time,
             rng: Optional[random.Random] = None):
    rng = rng if rng is not None else random.Random()
    return clock() + rng.random()
""")
    assert tree.active(rules=["seeded-determinism"]) == []


def test_determinism_seeded_random_is_clean(tree):
    tree.write("doorman_tpu/chaos/inj.py", """
import random


def make(seed):
    return random.Random(seed)
""")
    assert tree.active(rules=["seeded-determinism"]) == []


def test_determinism_unseeded_bare_random_flagged(tree):
    # Scope is import-derived: ctl.py is covered because the chaos
    # package (a derivation root) imports it, not because "admission/"
    # appears in a hand-kept prefix list.
    tree.write("doorman_tpu/admission/ctl.py", """
import random

RNG = random.Random()
""")
    tree.write("doorman_tpu/chaos/drive.py",
               "from doorman_tpu.admission import ctl\n")
    assert len(tree.active(rules=["seeded-determinism"])) == 1


def test_determinism_out_of_scope_module_ignored(tree):
    # Nothing chaos-reachable imports loadtest: exempt by construction.
    tree.write("doorman_tpu/loadtest/gen.py", """
import time


def now():
    return time.time()
""")
    assert tree.active(rules=["seeded-determinism"]) == []


# ---------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------

LOCKED = """
import threading


class Staging:
    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}  # guarded-by: self._lock

    def stage(self, rid, row):
        with self._lock:
            self._cache[rid] = row

    def take(self):
        with self._lock:
            out, self._cache = self._cache, {}
        return out
"""

UNLOCKED_TOUCH = LOCKED + """
    def peek(self, rid):
        return self._cache.get(rid)
"""


def test_lock_discipline_flags_unlocked_access(tree):
    tree.write("doorman_tpu/solver/staging.py", UNLOCKED_TOUCH)
    found = tree.active(rules=["lock-discipline"])
    assert len(found) == 1
    assert "guarded-by: self._lock" in found[0].message


def test_lock_discipline_with_lock_is_clean(tree):
    tree.write("doorman_tpu/solver/staging.py", LOCKED)
    assert tree.active(rules=["lock-discipline"]) == []


def test_lock_discipline_holds_lock_annotation(tree):
    tree.write("doorman_tpu/solver/staging.py", LOCKED + """
    def _evict_locked(self, rid):  # holds-lock: self._lock
        self._cache.pop(rid, None)
""")
    assert tree.active(rules=["lock-discipline"]) == []


def test_lock_discipline_nested_closures_do_not_inherit_lock(tree):
    # A callable defined under `with lock` but handed to an executor
    # runs later, lock-free: its access must still be flagged.
    tree.write("doorman_tpu/solver/staging.py", LOCKED + """
    def deferred(self, pool):
        with self._lock:
            def later():
                return self._cache.get(0)
            pool.submit(later)
""")
    found = tree.active(rules=["lock-discipline"])
    assert len(found) == 1


def test_lock_discipline_executor_mutation_without_lock(tree):
    tree.write("doorman_tpu/admission/window.py", """
class Window:
    def resolve(self, loop):
        def work():
            self.flushes = self.flushes + 1
        loop.run_in_executor(None, work)
""")
    found = tree.active(rules=["lock-discipline"])
    assert len(found) == 1
    assert "executor-submitted" in found[0].message


def test_lock_discipline_executor_mutation_under_lock_is_clean(tree):
    tree.write("doorman_tpu/admission/window.py", """
import threading


class Window:
    def __init__(self):
        self._lock = threading.Lock()

    def resolve(self, loop):
        def work():
            with self._lock:
                self.flushes = 1
        loop.run_in_executor(None, work)
""")
    assert tree.active(rules=["lock-discipline"]) == []


# ---------------------------------------------------------------------
# trace-phase-hygiene
# ---------------------------------------------------------------------


def test_phase_hygiene_flags_unknown_phase_name(tree):
    tree.write("doorman_tpu/solver/resident.py", """
def dispatch(self, ph):
    ph.lap("sweeep")
""")
    found = tree.active(rules=["trace-phase-hygiene"])
    assert len(found) == 1
    assert "sweeep" in found[0].message


def test_phase_hygiene_registry_names_are_clean(tree):
    tree.write("doorman_tpu/solver/resident.py", """
def dispatch(self, ph, tracer):
    ph.lap("sweep")
    with tracer.span("server.tick", cat="tick"):
        ph.lap("solve")
    tracer.instant("election.transition")
""")
    assert tree.active(rules=["trace-phase-hygiene"]) == []


def test_phase_hygiene_unknown_span_flagged(tree):
    tree.write("doorman_tpu/server/handlers.py", """
def handle(tracer):
    with tracer.span("sevrer.tick"):
        pass
""")
    assert len(tree.active(rules=["trace-phase-hygiene"])) == 1


def test_phase_hygiene_fstring_prefix_wildcards(tree):
    tree.write("doorman_tpu/server/handlers.py", """
def handle(tracer, method):
    with tracer.span(f"server.{method}"):
        pass
    with tracer.span(f"{method}.oops"):
        pass
""")
    found = tree.active(rules=["trace-phase-hygiene"])
    assert len(found) == 1
    assert "prefix.*" in found[0].message


def test_phase_hygiene_span_without_with_is_unmatched_begin(tree):
    tree.write("doorman_tpu/server/handlers.py", """
def handle(tracer):
    span = tracer.span("server.tick")
    return span
""")
    found = tree.active(rules=["trace-phase-hygiene"])
    assert len(found) == 1
    assert "without `with`" in found[0].message


def test_phase_hygiene_span_factory_idiom_allowed(tree):
    tree.write("doorman_tpu/server/handlers.py", """
def _rpc_span(tracer, method):
    return tracer.span(f"server.{method}")
""")
    assert tree.active(rules=["trace-phase-hygiene"]) == []


# ---------------------------------------------------------------------
# suppression + baseline semantics
# ---------------------------------------------------------------------


def test_same_line_allow_suppresses_only_that_rule(tree):
    tree.write("doorman_tpu/chaos/t.py", """
import time


def now():
    return time.time()  # doorman: allow[seeded-determinism] real clock
""")
    findings = tree.run(rules=["seeded-determinism"])
    assert len(findings) == 1 and findings[0].suppressed


def test_preceding_comment_line_allow(tree):
    tree.write("doorman_tpu/chaos/t.py", """
import time


def now():
    # doorman: allow[seeded-determinism] wall clock by design
    return time.time()
""")
    findings = tree.run(rules=["seeded-determinism"])
    assert len(findings) == 1 and findings[0].suppressed


def test_allow_for_other_rule_does_not_suppress(tree):
    tree.write("doorman_tpu/chaos/t.py", """
import time


def now():
    return time.time()  # doorman: allow[lock-discipline]
""")
    findings = tree.run(rules=["seeded-determinism"])
    assert len(findings) == 1 and not findings[0].suppressed


def test_baseline_absorbs_exactly_counted_findings(tree, tmp_path):
    tree.write("doorman_tpu/chaos/t.py", """
import time


def a():
    return time.time()
""")
    findings = tree.active(rules=["seeded-determinism"])
    baseline_path = tmp_path / "baseline.json"
    write_baseline(findings, baseline_path)

    # Same tree: fully baselined.
    findings = tree.active(rules=["seeded-determinism"])
    apply_baseline(findings, load_baseline(baseline_path))
    assert all(f.baselined for f in findings)

    # A SECOND copy of the same sin on a new line is NOT absorbed.
    tree.write("doorman_tpu/chaos/t.py", """
import time


def a():
    return time.time()


def b():
    return time.time()
""")
    findings = tree.active(rules=["seeded-determinism"])
    apply_baseline(findings, load_baseline(baseline_path))
    assert sum(1 for f in findings if f.baselined) == 1
    assert sum(1 for f in findings if not f.baselined) == 1


def test_baseline_survives_line_number_drift(tree, tmp_path):
    tree.write("doorman_tpu/chaos/t.py", "import time\nx = time.time()\n")
    baseline_path = tmp_path / "baseline.json"
    write_baseline(tree.active(rules=["seeded-determinism"]), baseline_path)
    # Push the finding 3 lines down; the (rule, path, snippet) key holds.
    tree.write(
        "doorman_tpu/chaos/t.py",
        "import time\n\n\n\nx = time.time()\n",
    )
    findings = tree.active(rules=["seeded-determinism"])
    apply_baseline(findings, load_baseline(baseline_path))
    assert len(findings) == 1 and findings[0].baselined


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def test_cli_exit_codes_and_json(tree, tmp_path, capsys):
    from tools.lint.cli import main

    tree.write("doorman_tpu/chaos/t.py", "import time\nx = time.time()\n")
    out_json = tmp_path / "findings.json"
    rc = main([
        "--root", str(tree.root), "--rule", "seeded-determinism",
        "--json", str(out_json),
    ])
    assert rc == 1
    payload = json.loads(out_json.read_text())
    assert payload["summary"]["findings"] == 1
    assert payload["findings"][0]["rule"] == "seeded-determinism"
    assert payload["findings"][0]["path"] == "doorman_tpu/chaos/t.py"

    # Baselining it turns the gate green.
    rc = main([
        "--root", str(tree.root), "--rule", "seeded-determinism",
        "--write-baseline",
    ])
    assert rc == 0
    rc = main(["--root", str(tree.root), "--rule", "seeded-determinism"])
    assert rc == 0
    # --no-baseline reports it again.
    rc = main([
        "--root", str(tree.root), "--rule", "seeded-determinism",
        "--no-baseline",
    ])
    assert rc == 1
    capsys.readouterr()


def test_cli_unknown_rule_is_usage_error(tree, capsys):
    from tools.lint.cli import main

    assert main(["--root", str(tree.root), "--rule", "no-such-rule"]) == 2
    capsys.readouterr()


def test_cli_list_rules(capsys):
    from tools.lint.cli import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in (
        "jit-closure-capture", "host-sync-in-hot-path",
        "fused-writer-discipline", "seeded-determinism",
        "lock-discipline", "trace-phase-hygiene",
        "lock-order", "device-sync-taint", "registry-coherence",
    ):
        assert rule in out


# ---------------------------------------------------------------------
# the acceptance criterion: the real repo is clean
# ---------------------------------------------------------------------


def test_real_repo_has_zero_active_findings():
    findings = run_lint(REPO_ROOT)
    apply_baseline(
        findings, load_baseline(REPO_ROOT / "tools" / "lint" / "baseline.json")
    )
    active = [f for f in findings if not f.suppressed and not f.baselined]
    assert active == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in active
    )


def test_real_repo_registries_are_mined():
    from tools.lint.core import RepoContext, load_files

    contexts, errors = load_files(REPO_ROOT)
    assert errors == []
    repo = RepoContext(REPO_ROOT, contexts)
    assert "AlgoKind" in repo.int_enum_classes
    assert "solve" in repo.phases and "download" in repo.phases
    assert "server.tick" in repo.span_names
    assert "CapacityServer._decide" in repo.tracked_writers
