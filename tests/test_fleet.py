"""Fleet runtime conformance (doorman_tpu/fleet, doc/operations.md).

The pins:

  * routing epochs — advance() computes the exact move diff over the
    tracked set, rejects no-ops, never moves a straddle;
  * the beat codec — ShardSummary <-> GetServerCapacity aggregate
    round-trips losslessly for integer weights (the wire beat carries
    compact per-band curves, never per-client rows);
  * BeatCore push-mode drain — a silent shard's share freezes (still
    charged against the pool), then its slack is re-offered only after
    the drain window, so Σ reported grants never exceeds capacity;
  * the autoscaler — hysteresis, cool-down, bound clamping, and the
    streak reset that prevents 2→3→2 flapping;
  * THE acceptance arc — live reshard 2→3 under churn on the
    deterministic in-process fleet: fed_capacity_sum holds pointwise
    on every tick of the handoff, healthy-resource clients see
    byte-unchanged grants, the moved resource's client keeps its grant
    across the ownership change, and the old owner gets an
    epoch-stamped redirect table;
  * discovery under a shard-count change — apply_epoch re-homes
    exactly the moved routes with at most one new Discovery
    resolution (counter-pinned: no stampede), and a stale-epoch client
    refreshing the old owner over real loopback gRPC is redirected and
    chases to the new owner;
  * the fleet chaos plans and the reshard_diurnal workload scenario
    are deterministic (byte-stable log hashes) and their gates hold.
"""

import asyncio

import pytest

import tests.conftest  # noqa: F401

from doorman_tpu.algorithms import Request
from doorman_tpu.chaos import get_plan
from doorman_tpu.chaos.runner import ChaosRunner
from doorman_tpu.client.client import Client
from doorman_tpu.federation import (
    FederatedClient,
    ShardDiscovery,
    ShardRouter,
    stable_shard,
)
from doorman_tpu.federation.reconcile import ShardSummary
from doorman_tpu.fleet import (
    Autoscaler,
    BeatCore,
    EpochRouter,
    FleetController,
    decode_summary,
    encode_summary,
    parse_shard_server_id,
    shard_server_id,
)
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer


def run(coro):
    return asyncio.run(coro)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


CONFIG = """
resources:
- identifier_glob: strad
  capacity: 120
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 100
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
"""


async def _make_batch_server(name, clock, shard=None):
    server = CapacityServer(
        name, TrivialElection(), mode="batch",
        minimum_refresh_interval=0.0, clock=clock, shard=shard,
        flightrec_capacity=0,
    )
    await server.load_config(parse_yaml_config(CONFIG))
    await asyncio.sleep(0)
    return server


# ----------------------------------------------------------------------
# Routing epochs
# ----------------------------------------------------------------------


def _rid_that(pred):
    for i in range(200):
        rid = f"ord-{i}"
        if pred(rid):
            return rid
    raise AssertionError("no resource id matched the predicate")


def test_epoch_router_move_diff_and_noop():
    stay = _rid_that(lambda r: stable_shard(r, 2) == stable_shard(r, 3))
    move = _rid_that(lambda r: stable_shard(r, 2) != stable_shard(r, 3))
    er = EpochRouter(2, straddle=["strad"], resources=[stay, move])
    assert er.epoch == 0
    change = er.advance(3)
    assert er.epoch == 1 and change.epoch == 1
    assert change.n_from == 2 and change.n_to == 3
    assert change.added == (2,) and change.removed == ()
    moved = {rid: (old, new) for rid, old, new in change.moved}
    assert move in moved
    assert moved[move] == (stable_shard(move, 2), stable_shard(move, 3))
    assert stay not in moved
    assert "strad" not in moved  # straddles re-split, never move
    log = change.as_log()
    assert log["epoch"] == 1 and log["from"] == 2 and log["to"] == 3
    with pytest.raises(ValueError, match="no-op"):
        er.advance(3)
    back = er.advance(2)
    assert back.added == () and back.removed == (2,)
    # The shrink diff is the grow diff reversed.
    assert {rid: (new, old) for rid, old, new in back.moved} == moved


def test_epoch_router_rejects_stranded_override():
    er = EpochRouter(3, overrides={"pinned": 2})
    with pytest.raises(ValueError):
        er.advance(2)  # override points past the new shard count
    assert er.epoch == 0  # failed advance publishes nothing


# ----------------------------------------------------------------------
# The beat codec
# ----------------------------------------------------------------------


def test_shard_server_id_round_trip():
    assert shard_server_id(3) == "fleet-shard-3"
    assert parse_shard_server_id("fleet-shard-3") == 3
    assert parse_shard_server_id("some-intermediate") is None
    assert parse_shard_server_id("fleet-shard-x") is None


def test_beat_codec_round_trips_summary():
    summary = ShardSummary(
        shard=1, wants=58.0, has=41.5, weight=7.0,
        breakpoints=((4.0, 8.0, 2.0), (10.0, 50.0, 5.0)),
    )
    req = encode_summary(summary, "strad")
    assert req.resource_id == "strad"
    assert req.has.capacity == 41.5
    # One band per breakpoint: index, weight, wants — O(curve), never
    # O(clients).
    assert [(b.priority, b.num_clients, b.wants) for b in req.wants] == [
        (0, 2, 8.0), (1, 5, 50.0),
    ]
    back = decode_summary(req, 1)
    assert back == summary  # integer weights: lossless round-trip


# ----------------------------------------------------------------------
# BeatCore: push-mode freeze -> decay -> re-offer
# ----------------------------------------------------------------------


def test_beat_core_freezes_silent_shard_then_reoffers():
    clock = FakeClock()
    core = BeatCore(
        lambda rid: (100.0, pb.Algorithm.PROPORTIONAL_SHARE, 5.0),
        expected=[0, 1], share_ttl=2.0, stale_after=2.0, clock=clock,
    )

    def report(shard, wants, has):
        return core.offer(shard, "strad", ShardSummary(
            shard=shard, wants=wants, has=has, weight=1.0,
            breakpoints=((wants, wants, 1.0),),
        ))

    has = {0: 0.0, 1: 0.0}
    for _ in range(4):
        for shard in (0, 1):
            share, expiry = report(shard, 80.0, has[shard])
            assert expiry == clock() + 2.0
            has[shard] = min(80.0, share)
        clock.advance(1.0)
    # Symmetric overload: the fleet splits evenly.
    assert has[0] == has[1] == pytest.approx(50.0)

    # Shard 1 goes silent. Its share freezes — still charged — so
    # shard 0 can never be offered the frozen slack early.
    frozen = has[1]
    reoffered_at = None
    for step in range(12):
        clock.advance(1.0)
        share, _ = report(0, 80.0, has[0])
        assert share + frozen <= 100.0 + 1e-9 or share > 60.0
        if share > 60.0 and reoffered_at is None:
            reoffered_at = step
        has[0] = min(80.0, share)
        if reoffered_at is None:
            # While the share is frozen the silent shard's last
            # reported grants are still live, so the wire-plane
            # capacity sum covers them; after the drain window those
            # leases have expired and only the survivor's grants count.
            total = core.has_sums()["strad"]
            assert total <= 100.0 + 1e-9, (step, total)
    # The slack came back only after expiry + lease drained the frozen
    # share (share_ttl 2 + lease 5), and then the survivor got it all.
    assert reoffered_at is not None and reoffered_at >= 6
    assert has[0] == pytest.approx(80.0)


# ----------------------------------------------------------------------
# Autoscaler
# ----------------------------------------------------------------------


def _verdict(status, margin=0.0):
    return {"slo": "x", "status": status, "margin": margin}


def test_autoscaler_hysteresis_and_cooldown():
    a = Autoscaler(min_shards=2, max_shards=4, step=1, hysteresis=3,
                   cooldown=6, shrink_margin=0.1)
    assert a.observe(0, [_verdict("fail")], 2) is None
    assert a.observe(1, [_verdict("fail")], 2) is None
    assert a.observe(2, [_verdict("fail")], 2) == 3  # streak of 3
    # Cool-down: an immediate second fail-streak cannot fire.
    for t in (3, 4, 5):
        assert a.observe(t, [_verdict("fail")], 3) is None
    for t in (6, 7):
        assert a.observe(t, [_verdict("fail")], 3) is None
    assert a.observe(8, [_verdict("fail")], 3) == 4  # cool-down lapsed
    # Bound clamp: at max, a fail streak decides nothing (no no-op
    # reshard, no churn).
    for t in range(14, 20):
        assert a.observe(t, [_verdict("fail")], 4) is None
    assert [d["reason"] for d in a.decisions] == [
        "grow:fail-streak", "grow:fail-streak",
    ]


def test_autoscaler_shrink_needs_margin_and_flip_resets_streak():
    a = Autoscaler(min_shards=1, max_shards=4, step=1, hysteresis=2,
                   cooldown=0, shrink_margin=0.1)
    # Passing without headroom is HOLD, not shrink.
    assert a.observe(0, [_verdict("pass", 0.05)], 3) is None
    assert a.observe(1, [_verdict("pass", 0.05)], 3) is None
    # A flip resets the streak: pass, fail, pass never fires.
    assert a.observe(2, [_verdict("pass", 0.5)], 3) is None
    assert a.observe(3, [_verdict("fail")], 3) is None
    assert a.observe(4, [_verdict("pass", 0.5)], 3) is None
    assert a.observe(5, [_verdict("pass", 0.5)], 3) == 2
    assert a.decisions[-1]["reason"] == "shrink:margin-streak"
    # no_data verdicts are not a signal either way.
    assert a.observe(6, [_verdict("no_data")], 2) is None
    assert a.observe(7, [_verdict("no_data")], 2) is None


# ----------------------------------------------------------------------
# THE acceptance arc: live reshard 2 -> 3 under churn
# ----------------------------------------------------------------------

WARMUP = 6
RESHARD_TICK = 6
TOTAL = 16


def test_live_reshard_2_to_3_is_lease_continuous():
    """Pointwise fed_capacity_sum through the handoff, byte-unchanged
    grants for healthy resources, grant continuity for the moved
    resource, redirect tables on the old owner."""
    stay = _rid_that(lambda r: stable_shard(r, 2) == stable_shard(r, 3))
    move = _rid_that(lambda r: stable_shard(r, 2) != stable_shard(r, 3))

    async def body():
        clock = FakeClock()
        servers = {
            i: await _make_batch_server(f"s{i}", clock, shard=i)
            for i in range(3)
        }
        fleet = FleetController(
            servers, straddle=["strad"], active=2,
            addrs={i: f"addr-{i}" for i in range(3)},
            share_ttl=2.0, clock=clock,
        )
        fleet.note_resources([stay, move])
        grants = {}

        def decide(shard, rid, client, wants):
            lease, _ = servers[shard]._decide(
                rid, Request(client, grants.get((rid, client), 0.0),
                             wants),
            )
            grants[(rid, client)] = lease.has
            return lease.has

        try:
            for tick in range(TOTAL):
                if tick == RESHARD_TICK:
                    change = fleet.reshard(3)
                    assert fleet.active == 3 and fleet.epoch == 1
                    moved = {r for r, _o, _n in change.moved}
                    assert move in moved and stay not in moved
                    # The old owner's redirect table points the moved
                    # resource at the new owner's dial address.
                    old, new = (
                        stable_shard(move, 2), stable_shard(move, 3),
                    )
                    assert servers[old]._fleet_routing[move] == (
                        f"addr-{new}"
                    )
                    assert move not in servers[new]._fleet_routing
                # The beat runs BEFORE refreshes land (runner order):
                # a freshly activated shard has its share installed
                # before it serves a single straddle request.
                installed = fleet.reconcile_once()
                assert set(installed["strad"]) == set(
                    range(fleet.active)
                )
                # Overloaded straddle churn: demand outgrows capacity,
                # and a NEW client lands on the new shard mid-handoff.
                decide(0, "strad", "c-a", 100.0)
                decide(1, "strad", "c-b", 80.0)
                if tick > RESHARD_TICK:
                    decide(2, "strad", "c-new", 50.0)
                # Healthy ordinary resource: underloaded, unmoved.
                healthy = decide(
                    stable_shard(stay, 2), stay, "c-stay", 25.0
                )
                assert healthy == 25.0  # byte-unchanged, every tick
                # The moved resource: its client follows the router.
                owner = fleet.router.shard_of(move)
                moved_has = decide(owner, move, "c-move", 40.0)
                assert moved_has == 40.0  # continuity across the move
                for server in servers.values():
                    await server.tick_once()
                clock.advance(1.0)
                # fed_capacity_sum, pointwise over EVERY provisioned
                # shard (a draining shard's grants still count).
                total = sum(
                    s.resources["strad"].store.sum_has
                    for s in servers.values()
                    if "strad" in s.resources
                )
                assert total <= 120.0 + 1e-6, (tick, total)
            # The handoff converged: all three shards hold installed
            # shares and the new client is being served.
            assert grants[("strad", "c-new")] > 0.0
            # The moved resource lives on its new owner's store now.
            new = stable_shard(move, 3)
            assert servers[new].resources[move].store.get(
                "c-move"
            ).has == 40.0
        finally:
            for server in servers.values():
                await server.stop()

    run(body())


# ----------------------------------------------------------------------
# Discovery under a shard-count change (no stampede, exact re-homing)
# ----------------------------------------------------------------------


def test_apply_epoch_rehomes_exactly_the_moved_routes():
    stay = _rid_that(lambda r: stable_shard(r, 2) == stable_shard(r, 3))
    move = _rid_that(lambda r: stable_shard(r, 2) != stable_shard(r, 3))

    async def body():
        import random

        clock = FakeClock()
        resolved = []

        async def resolver(shard, seeds):
            resolved.append(shard)
            return f"addr-{shard}"

        disc = ShardDiscovery(
            {i: f"seed-{i}" for i in range(3)}, ttl=1e6, jitter=0.0,
            clock=clock, rng=random.Random(7), resolver=resolver,
        )
        er = EpochRouter(2, straddle=["strad"],
                         resources=[stay, move])
        fed = FederatedClient(
            er.router, disc, client_id="fed-c", background=False,
            clock=clock, minimum_refresh_interval=0.0,
        )
        res_stay = await fed.resource(stay, 10.0)
        res_move = await fed.resource(move, 20.0)
        res_strad = await fed.resource(
            "strad", 5.0, shard=stable_shard(move, 2)
        )
        clients_before = dict(fed._clients)
        base = len(resolved)

        change = er.advance(3)
        out = await fed.apply_epoch(
            er.router, [r for r, _o, _n in change.moved]
        )
        # Exactly the claimed moved route re-homed; the straddle and
        # the stable resource never move.
        assert out["rehomed"] == [move]
        new_owner = stable_shard(move, 3)
        assert fed._clients[new_owner].resources[move] is res_move
        for shard, client in clients_before.items():
            assert fed._clients[shard] is client  # no reconnect storm
        assert res_stay._client is clients_before[stable_shard(stay, 2)]
        assert res_strad._client.resources["strad"] is res_strad
        # Counter-pinned: the epoch bump cost AT MOST one Discovery
        # resolution (the new owner), not one per claimed resource.
        assert len(resolved) - base <= 1
        # A second application of the same epoch is a no-op.
        out2 = await fed.apply_epoch(er.router, [move])
        assert out2["rehomed"] == []
        await fed.close()

    run(body())


def test_stale_epoch_refresh_chases_redirect_to_new_owner():
    """Loopback gRPC: a client with the OLD router refreshing the old
    owner gets a fleet mastership redirect and chases to the new
    owner, which carries the reported grant across (lease
    continuity)."""

    async def body():
        old = CapacityServer(
            "old-owner", TrivialElection(), mode="immediate",
            minimum_refresh_interval=0.0,
        )
        new = CapacityServer(
            "new-owner", TrivialElection(), mode="immediate",
            minimum_refresh_interval=0.0,
        )
        old_port = await old.start(0, host="127.0.0.1")
        new_port = await new.start(0, host="127.0.0.1")
        for server, port in ((old, old_port), (new, new_port)):
            await server.load_config(parse_yaml_config(CONFIG))
            await asyncio.sleep(0)
            server.current_master = f"127.0.0.1:{port}"
        client = Client(
            f"127.0.0.1:{old_port}", "stale-client",
            minimum_refresh_interval=0.0,
        )
        try:
            res = await client.resource("moved-rid", wants=25.0)
            assert await client.refresh_once()
            assert res.current_capacity() == 25.0
            assert "moved-rid" in old.resources

            # The reshard publishes epoch 1: this shard no longer owns
            # the resource; the table names the new owner.
            old.set_fleet_routing(
                1, {"moved-rid": f"127.0.0.1:{new_port}"}
            )
            # An out-of-order epoch-0 install must not roll it back.
            old.set_fleet_routing(0, {})
            assert old._fleet_routing == {
                "moved-rid": f"127.0.0.1:{new_port}"
            }

            # The stale client's next refresh chases the redirect; the
            # one after lands the refresh on the new owner.
            ok = await client.refresh_once()
            if not ok:
                assert await client.refresh_once()
            assert old.fed_stats["fleet_redirects"] >= 1
            assert res.current_capacity() == 25.0  # never lapsed
            assert new.resources["moved-rid"].store.get(
                "stale-client"
            ).has == 25.0
        finally:
            await client.close()
            await old.stop()
            await new.stop()

    run(body())


# ----------------------------------------------------------------------
# Chaos plans + workload scenario: determinism and gates
# ----------------------------------------------------------------------


def _run_plan(name):
    runner = ChaosRunner(get_plan(name))
    verdict = asyncio.run(runner.run())
    return verdict, runner.log


@pytest.mark.parametrize(
    "name", ["fleet_reshard_live", "fleet_reshard_partition"]
)
def test_fleet_chaos_plans_hold_and_are_deterministic(name):
    v, log = _run_plan(name)
    assert v["ok"], v["violations"]
    assert v["violations"] == []
    epochs = [e for e in log if e[1] == "fleet_epoch"]
    assert epochs, "plan must actually publish routing epochs"
    again, _ = _run_plan(name)
    assert again["log_sha256"] == v["log_sha256"]


def test_reshard_diurnal_scenario_arcs_2_4_2():
    from doorman_tpu.workload.scenarios import run_scenario

    v = run_scenario("reshard_diurnal", seed=0)
    assert v["ok"], v["slo"]["verdicts"]
    assert v["summary"]["epoch_changes"] == 2.0
    assert v["summary"]["fed_capacity_violations"] == 0.0
    again = run_scenario("reshard_diurnal", seed=0)
    assert again["log_sha256"] == v["log_sha256"]
