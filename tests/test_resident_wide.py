"""Wide (chunked) resident solver vs the BatchSolver ground truth.

The wide path (solver/resident_wide.py) spans a resource across several
device rows and moves slot-granular deltas; with rotate_ticks=1 and
sequential dispatch+collect it must track the full-reupload BatchSolver
tick for tick through demand churn, releases, new clients, expiry
sweeps, and learning mode. (Comparison is allclose, not byte-equal: the
two-level chunk reduction re-associates float sums.)"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.core.resource import Resource
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.solver.batch import BatchSolver
from doorman_tpu.solver.resident_wide import WideResidentSolver

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

KINDS = [
    pb.Algorithm.NO_ALGORITHM,
    pb.Algorithm.STATIC,
    pb.Algorithm.PROPORTIONAL_SHARE,
    pb.Algorithm.FAIR_SHARE,
]

RTOL = 1e-9  # two-level float reassociation, f64
# Near-zero grants carry ABSOLUTE reassociation noise at the resource's
# capacity scale (caps here reach 500: one reassociated f64 sum leaves
# O(cap * eps * depth) ~ 1e-12), so the absolute floor sits at 1e-9 —
# still nine decades below the smallest meaningful grant in these
# worlds, while rtol pins every value of real magnitude.
ATOL = 1e-9


def make_world(clock, n_res=4, n_clients=21, seed=3):
    """Resources wider than the test chunk width (8), so each spans
    several chunk rows."""
    rng = np.random.default_rng(seed)
    engine = native.StoreEngine(clock=clock)
    resources = []
    for r in range(n_res):
        tpl = pb.ResourceTemplate(
            identifier_glob=f"res{r}",
            capacity=float(rng.integers(50, 500)),
            algorithm=pb.Algorithm(
                kind=int(KINDS[r % len(KINDS)]),
                lease_length=60,
                refresh_interval=5,
            ),
        )
        res = Resource(
            f"res{r}", tpl, clock=clock, store_factory=engine.store
        )
        resources.append(res)
        for c in range(n_clients):
            res.store.assign(
                f"c{r}_{c}", 60.0, 5.0, 0.0,
                float(rng.integers(1, 100)), 1,
            )
    return engine, resources


def all_leases(resources):
    out = {}
    for res in resources:
        for client, lease in res.store.items():
            out[(res.id, client)] = (
                lease.has, lease.wants, lease.subclients,
            )
    return out


def assert_close(a, b, msg=""):
    assert a.keys() == b.keys(), f"membership diverged {msg}"
    for key in a:
        np.testing.assert_allclose(
            a[key], b[key], rtol=RTOL, atol=ATOL,
            err_msg=f"{msg} lease {key}",
        )


def churn(resources, step, rng):
    res = resources[step % len(resources)]
    i = resources.index(res)
    res.store.assign(
        f"c{i}_0", 60.0, 5.0, res.store.get(f"c{i}_0").has,
        float(rng.integers(1, 200)), 1,
    )
    if step % 3 == 1:
        res2 = resources[(step * 7) % len(resources)]
        res2.store.release(f"c{resources.index(res2)}_1")
    if step % 3 == 2:
        res3 = resources[(step * 5) % len(resources)]
        res3.store.assign(
            f"new{step}_{resources.index(res3)}", 60.0, 5.0, 0.0,
            float(rng.integers(1, 50)), 2,
        )


def test_wide_matches_batch_solver_tick_for_tick():
    t = [1000.0]
    clock = lambda: t[0]
    eng_a, res_a = make_world(clock)
    eng_b, res_b = make_world(clock)
    wide = WideResidentSolver(
        eng_a, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8,
    )
    batch = BatchSolver(dtype=np.float64, clock=clock)
    rng_a, rng_b = (np.random.default_rng(99) for _ in range(2))
    for step in range(8):
        churn(res_a, step, rng_a)
        churn(res_b, step, rng_b)
        if step == 4:
            res_a[2].learning_mode_end = t[0] + 100
            res_b[2].learning_mode_end = t[0] + 100
        wide.step(res_a, config_epoch=1 if step >= 4 else 0)
        batch.tick(res_b)
        assert_close(
            all_leases(res_a), all_leases(res_b), f"tick {step}"
        )
        t[0] += 1.0


def test_wide_rotation_converges_to_batch_fixpoint():
    """rotate_ticks>1: wants-driven movement rides the rotation; with
    demand frozen the stores must reach the batch fixpoint."""
    t = [500.0]
    clock = lambda: t[0]
    eng_a, res_a = make_world(clock, seed=11)
    eng_b, res_b = make_world(clock, seed=11)
    wide = WideResidentSolver(
        eng_a, dtype=np.float64, clock=clock, rotate_ticks=4,
        chunk_width=8,
    )
    batch = BatchSolver(dtype=np.float64, clock=clock)
    for _ in range(12):
        wide.step(res_a)
        batch.tick(res_b)
        t[0] += 1.0
    assert_close(all_leases(res_a), all_leases(res_b))


def test_chunk_version_guard_skips_only_the_stale_chunk():
    """A mid-flight membership change must skip exactly the chunks whose
    slot order moved — other chunks of the SAME resource still apply."""
    t = [100.0]
    clock = lambda: t[0]
    engine, resources = make_world(clock, n_res=1, n_clients=21)
    wide = WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8,
    )
    wide.step(resources)  # settle: 3 chunks
    # A chunk-1 client's demand moves, so this tick's solve produces a
    # NEW grant for it (res0 is NO_ALGORITHM: grant == wants) — the
    # applied chunk must visibly write it.
    old_has = resources[0].store.get("c0_9").has
    resources[0].store.assign("c0_9", 60.0, 5.0, old_has, 999.0, 1)
    handle = wide.dispatch(resources)
    # Release c0_1 (slot 1, chunk 0); last slot 20 is chunk 2.
    resources[0].store.release("c0_1")
    before = all_leases(resources)
    applied = wide.collect(handle)
    after = all_leases(resources)
    # Chunks 0 and 2 skipped, chunk 1 applied — and its write is real.
    assert applied == 1
    assert after[("res0", "c0_9")][0] == 999.0
    assert before[("res0", "c0_9")][0] == old_has != 999.0
    for c in list(range(0, 8)) + list(range(16, 21)):
        key = ("res0", f"c0_{c}")
        if key in after:
            assert after[key] == before[key], f"stale chunk wrote {key}"
    # The re-marked slots re-deliver next tick.
    wide.step(resources)
    t[0] += 1.0
    wide.step(resources)
    assert wide.ticks >= 3


def make_prop_world(clock, n_clients=21, cap=1000.0, wants=100.0):
    """One oversubscribed PROPORTIONAL_SHARE resource spanning chunks."""
    engine = native.StoreEngine(clock=clock)
    tpl = pb.ResourceTemplate(
        identifier_glob="res0",
        capacity=cap,
        algorithm=pb.Algorithm(
            kind=pb.Algorithm.PROPORTIONAL_SHARE,
            lease_length=60,
            refresh_interval=5,
        ),
    )
    res = Resource("res0", tpl, clock=clock, store_factory=engine.store)
    for c in range(n_clients):
        res.store.assign(f"c0_{c}", 60.0, 5.0, 0.0, wants, 1)
    return engine, [res]


def test_capacity_cut_reaches_store_within_one_tick():
    """A config-epoch bump (capacity cut) delivers ALL the resource's
    chunks the very next tick — not after the rotation."""
    t = [50.0]
    clock = lambda: t[0]
    engine, resources = make_prop_world(clock)
    wide = WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=64,
        chunk_width=8,
    )
    for _ in range(3):
        wide.step(resources)
        t[0] += 1.0
    sum_before = resources[0].store.sum_has
    # Capacity cut via template mutation + epoch bump.
    resources[0].template.capacity = 10.0
    wide.step(resources, config_epoch=1)
    sum_after = resources[0].store.sum_has
    assert sum_after <= 10.0 + 1e-9, (
        f"cut not delivered same-tick: sum_has {sum_before} -> {sum_after}"
    )


def test_growth_past_allocated_chunks_rebuilds():
    t = [10.0]
    clock = lambda: t[0]
    engine, resources = make_world(clock, n_res=1, n_clients=16, seed=5)
    wide = WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8,
    )
    wide.step(resources)
    assert wide._R == 2
    # Grow past 2 chunks x 8 slots.
    for c in range(16, 20):
        resources[0].store.assign(f"g{c}", 60.0, 5.0, 0.0, 5.0, 1)
    wide.step(resources)
    assert wide._R == 3
    # Grants still correct vs a fresh batch world.
    eng_b = native.StoreEngine(clock=clock)
    res_b = Resource(
        "res0", resources[0].template, clock=clock,
        store_factory=eng_b.store,
    )
    for client, lease in resources[0].store.items():
        res_b.store.assign(
            client, 60.0, 5.0, 0.0, lease.wants, lease.subclients
        )
    BatchSolver(dtype=np.float64, clock=clock).tick([res_b])
    wide.step(resources)
    a = {c: l.has for c, l in resources[0].store.items()}
    b = {c: l.has for c, l in res_b.store.items()}
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=RTOL, err_msg=k)


def test_expiry_sweep_flows_through():
    """Expired leases vanish from the store AND from the device table
    (the swept slots re-upload as inactive)."""
    t = [0.0]
    clock = lambda: t[0]
    engine, resources = make_prop_world(clock)
    wide = WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8,
    )
    # Short-lease client that will lapse.
    resources[0].store.assign("short", 5.0, 5.0, 0.0, 50.0, 1)
    wide.step(resources)
    assert resources[0].store.has_client("short")
    t[0] = 10.0  # past the 5s lease
    wide.step(resources)
    assert not resources[0].store.has_client("short")
    # The freed share redistributes; totals stay capped.
    wide.step(resources)
    cap = resources[0].template.capacity
    assert resources[0].store.sum_has <= cap * (1 + 1e-9)


def test_idle_fast_path_engages():
    t = [1.0]
    clock = lambda: t[0]
    engine, resources = make_world(clock, n_res=2, n_clients=21, seed=13)
    wide = WideResidentSolver(
        engine, dtype=np.float64, clock=clock, rotate_ticks=2,
        chunk_width=8,
    )
    for _ in range(12):
        wide.step(resources)
        t[0] += 1.0
    assert wide.idle_ticks > 0
    # Any write resumes real ticks.
    resources[0].store.assign("c0_0", 60.0, 5.0, 0.0, 123.0, 1)
    idle_before = wide.idle_ticks
    wide.step(resources)
    assert wide.idle_ticks == idle_before


def test_boundary_width_exact_multiple():
    """Population exactly chunk_width and chunk_width+1: the chunk map
    sizes correctly on both sides of the boundary."""
    t = [1.0]
    clock = lambda: t[0]
    for n, want_chunks in ((8, 1), (9, 2)):
        engine = native.StoreEngine(clock=clock)
        tpl = pb.ResourceTemplate(
            identifier_glob="res",
            capacity=100.0,
            algorithm=pb.Algorithm(
                kind=pb.Algorithm.PROPORTIONAL_SHARE,
                lease_length=60, refresh_interval=5,
            ),
        )
        res = Resource("res", tpl, clock=clock, store_factory=engine.store)
        for c in range(n):
            res.store.assign(f"c{c}", 60.0, 5.0, 0.0, 20.0, 1)
        wide = WideResidentSolver(
            engine, dtype=np.float64, clock=clock, rotate_ticks=1,
            chunk_width=8,
        )
        wide.step([res])
        assert wide._R == want_chunks, (n, wide._R)
        assert res.store.sum_has == pytest.approx(
            min(100.0, 20.0 * n), rel=1e-9
        )


def test_drain_remove_pack_interleaving_converges():
    """The documented one-tick UPLOAD inconsistency window: a
    swap-remove landing between dispatch's slot drain and its
    pack_slots read pairs the new occupant's wants with the old
    occupant's device lanes for that solve. The chunk-version guard
    (read before the pack) must block the skewed chunks' write-back,
    and the re-marked slots must re-deliver a consistent solve — the
    stores converge to the batch fixpoint within the following ticks.
    """
    t = [200.0]
    clock = lambda: t[0]
    eng_a, res_a = make_world(clock, n_res=1, n_clients=21, seed=7)
    eng_b, res_b = make_world(clock, n_res=1, n_clients=21, seed=7)
    wide = WideResidentSolver(
        eng_a, dtype=np.float64, clock=clock, rotate_ticks=1,
        chunk_width=8,
    )
    batch = BatchSolver(dtype=np.float64, clock=clock)
    wide.step(res_a)
    batch.tick(res_b)
    t[0] += 1.0

    # Wants-only churn dirties c0_5's slot (level 1)...
    old_has = res_a[0].store.get("c0_5").has
    res_a[0].store.assign("c0_5", 60.0, 5.0, old_has, 777.0, 1)

    # ... and the swap-remove lands BETWEEN the drain and the pack:
    # the first pack_slots of this dispatch releases c0_5, so the
    # drained slot index now holds the swapped-in occupant.
    orig_pack = eng_a.pack_slots
    fired = []

    def racing_pack(rid, slots):
        if not fired:
            fired.append(True)
            res_a[0].store.release("c0_5")
        return orig_pack(rid, slots)

    eng_a.pack_slots = racing_pack
    try:
        handle = wide.dispatch(res_a)
    finally:
        eng_a.pack_slots = orig_pack
    wide.collect(handle)
    assert fired, "the interleaved release never raced the pack"

    # Mirror world: same net operations, batch ground truth.
    old_has_b = res_b[0].store.get("c0_5").has
    res_b[0].store.assign("c0_5", 60.0, 5.0, old_has_b, 777.0, 1)
    res_b[0].store.release("c0_5")

    for _ in range(3):
        t[0] += 1.0
        wide.step(res_a)
        batch.tick(res_b)
    assert_close(all_leases(res_a), all_leases(res_b))
