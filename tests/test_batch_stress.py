"""Batch mode under concurrent load: hundreds of clients hammering a
native-store batch server while its resident tick loop runs.

Capability parity with the reference's load-oriented server tests
(go/server/doorman/server_test.go churn scenarios), recast for the
batched tick design: grants must stay capacity-safe under churn, and the
asyncio event loop must stay responsive while tick phases run in the
executor (the engine is mutex-guarded C++, so handlers never block on
more than one engine call)."""

import asyncio
import time

import grpc
import numpy as np
import pytest

import tests.conftest  # noqa: F401

from doorman_tpu import native
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import CapacityStub
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import TrivialElection
from doorman_tpu.server.server import CapacityServer

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native engine unavailable"
)

CONFIG = """
resources:
- identifier_glob: "shared*"
  capacity: 1000
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 500
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""

N_CLIENTS = 200
DURATION = 3.0


def test_batch_native_stress_grants_and_loop_responsiveness():
    async def body():
        server = CapacityServer(
            "stress", TrivialElection(), mode="batch", tick_interval=0.05,
            minimum_refresh_interval=0.0, native_store=True,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        server.current_master = f"127.0.0.1:{port}"
        addr = f"127.0.0.1:{port}"
        rng = np.random.default_rng(5)
        errors = []
        deadline = [0.0]

        def resource_of(i):
            return "shared0" if i % 2 == 0 else f"fair{i % 5}"

        def request(i, wants, has):
            req = pb.GetCapacityRequest(client_id=f"c{i}")
            rr = req.resource.add()
            rr.resource_id = resource_of(i)
            rr.wants = wants
            rr.has.capacity = has  # echo the last grant, like a real client
            return req

        # Phase 1: prime every client's lease, then let the resident
        # solver warm up (the first dispatches compile; membership
        # growth rebuilds the device tables — all cold-start work that
        # must not eat the storm window).
        wants_of = {i: float(rng.integers(1, 50)) for i in range(N_CLIENTS)}
        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)
            for i in range(N_CLIENTS):
                await stub.GetCapacity(request(i, wants_of[i], 0.0))
        for _ in range(300):
            if server._resident is not None and server._resident.ticks >= 2:
                break
            await asyncio.sleep(0.1)
        assert server._resident is not None and server._resident.ticks >= 2

        async def client_loop(i):
            wants = wants_of[i]
            has = 0.0
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                while time.monotonic() < deadline[0]:
                    try:
                        out = await stub.GetCapacity(request(i, wants, has))
                        has = out.response[0].gets.capacity
                        if has < -1e-9:
                            errors.append(f"negative grant {has}")
                    except grpc.aio.AioRpcError as e:  # pragma: no cover
                        errors.append(str(e.code()))
                    await asyncio.sleep(0.01 + 0.02 * (i % 3))

        async def probe_loop(latencies):
            """The responsiveness probe: Discovery is pure event-loop
            work, so its latency measures handler starvation while tick
            phases run in the executor."""
            async with grpc.aio.insecure_channel(addr) as ch:
                stub = CapacityStub(ch)
                while time.monotonic() < deadline[0]:
                    t0 = time.perf_counter()
                    await stub.Discovery(pb.DiscoveryRequest())
                    latencies.append(time.perf_counter() - t0)
                    await asyncio.sleep(0.02)

        ticks_before = server._resident.ticks
        deadline[0] = time.monotonic() + DURATION
        latencies = []
        tasks = [
            asyncio.create_task(client_loop(i)) for i in range(N_CLIENTS)
        ]
        tasks.append(asyncio.create_task(probe_loop(latencies)))
        try:
            await asyncio.gather(*tasks)
            # Tick progress: with 200 client loops and the tick
            # executor sharing one core, scheduler fairness — not the
            # server — decides how many ticks land inside the storm
            # window itself (observed 0 under full-suite load on a
            # 1-core container, ~60 solo — the same boundary-flake
            # shape as the probe bounds below). So allow a post-storm
            # grace: the loop must resume its cadence promptly once
            # the RPC pressure stops, which is the non-wedged claim
            # the tick floor actually carries.
            grace = time.monotonic() + 10.0
            while (server._resident.ticks - ticks_before <= 3
                   and time.monotonic() < grace):
                await asyncio.sleep(0.1)
        finally:
            await server.stop()

        assert not errors, errors[:5]
        assert server._resident.ticks - ticks_before > 3
        # Capacity safety after churn: the solved table never
        # oversubscribes a resource.
        for rid, res in server.resources.items():
            cap = res.template.capacity
            assert res.store.sum_has <= cap + 1e-6, (
                f"{rid}: {res.store.sum_has} > {cap}"
            )
        # Event loop responsiveness: with ~200 concurrent client loops
        # on one asyncio loop, Discovery stays well under the tick
        # interval's worth of stall.
        lat = np.array(latencies)
        # Each probe cycle is ~(0.02s sleep + Discovery latency); under
        # load ~20 cycles fit the 3s window, so demanding >20 sat right
        # on the boundary and flaked — and at ~700 collected tests the
        # 1-core container's per-cycle latency under full-suite load
        # reached ~0.5s, fitting only ~6 cycles. A handful of samples
        # still exercises the median/max bounds that carry the actual
        # claim.
        assert len(lat) >= 5
        # The median bound is a box-responsiveness ceiling, not the
        # claim itself (the max bound below is): 0.15 sat right at a
        # 1-core container's observed median once the collected suite
        # grew past ~550 tests, and ~0.48 was observed past ~700 (heap
        # pressure at collection time, not this test's code path — it
        # passes solo with large margin), the same boundary-flake
        # shape as the sample-count bound above.
        assert float(np.median(lat)) < 0.8, float(np.median(lat))
        assert float(lat.max()) < 2.0, float(lat.max())

        # Steady-state grant correctness for the contended resource:
        # shared0 holds 100 clients; proportional share rebalances to
        # capacity * wants / sum_wants when oversubscribed, or full
        # wants otherwise — every grant must be within that bound.
        res = server.resources["shared0"]
        sum_wants = res.store.sum_wants
        cap = res.template.capacity
        for client, lease in res.store.items():
            bound = (
                lease.wants
                if sum_wants <= cap
                else lease.wants * cap / sum_wants
            )
            assert lease.has <= bound + 1e-6

    asyncio.run(body())


def test_resident_overflow_repartitions_to_wide_under_live_traffic():
    """Drive a batch+native server ACROSS the ResidentOverflow
    re-partition under live gRPC traffic. A resource starts near
    DENSE_MAX_K width (narrow resident path active), then grows past it
    mid-traffic; the next dispatch raises inside the executor, the
    server runs that one tick through the BatchSolver, re-partitions
    (server.py resident_or_fallback), and the WIDE chunked resident
    solver takes the resource over — the resident fast path stays on at
    any width, and no grant may be lost or doubled across the switch."""
    from doorman_tpu.solver.batch import DENSE_MAX_K

    config = parse_yaml_config(
        """
resources:
- identifier_glob: "big"
  capacity: 100000
  algorithm: {kind: PROPORTIONAL_SHARE, lease_length: 60,
              refresh_interval: 1, learning_mode_duration: 0}
- identifier_glob: "*"
  capacity: 500
  algorithm: {kind: FAIR_SHARE, lease_length: 60, refresh_interval: 1,
              learning_mode_duration: 0}
"""
    )

    async def body():
        server = CapacityServer(
            "overflow", TrivialElection(), mode="batch",
            tick_interval=0.05, minimum_refresh_interval=0.0,
            native_store=True,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(config)
        server.current_master = f"127.0.0.1:{port}"
        addr = f"127.0.0.1:{port}"

        def request(i, wants=5.0):
            req = pb.GetCapacityRequest(client_id=f"c{i}")
            rr = req.resource.add()
            rr.resource_id = "big"
            rr.wants = wants
            return req

        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)
            # 30 live gRPC clients prime the resource...
            for i in range(30):
                await stub.GetCapacity(request(i))
            # ...and a bulk load brings it NEAR the dense cap (the
            # engine is the server's real store of record; this is what
            # thousands of RPC handlers would have written).
            engine = server._store_factory.__self__
            res = server.resources["big"]
            near = DENSE_MAX_K - 100
            rids = np.full(near, res.store._rid, np.int32)
            cids = np.array(
                [engine.client_handle(f"bulk{i}") for i in range(near)],
                np.int64,
            )
            engine.bulk_assign(
                rids, cids, np.full(near, time.time() + 60.0),
                np.full(near, 1.0), np.zeros(near),
                np.full(near, 2.0), np.ones(near, np.int32),
            )
            # The resident path must carry this near-max width.
            for _ in range(300):
                if server._resident is not None and server._resident.ticks >= 3:
                    break
                await asyncio.sleep(0.05)
            assert server._resident is not None
            assert server._resident.ticks >= 3
            assert server._resident_ok
            width = len(res.store)
            assert width > DENSE_MAX_K - 200

            errors = []
            stop = [False]

            async def client_loop(i):
                has = 0.0
                while not stop[0]:
                    try:
                        out = await stub.GetCapacity(request(i))
                        has = out.response[0].gets.capacity
                        if has < -1e-9:
                            errors.append(f"negative grant {has}")
                    except grpc.aio.AioRpcError as e:  # pragma: no cover
                        errors.append(str(e.code()))
                    await asyncio.sleep(0.02)

            loops = [asyncio.create_task(client_loop(i)) for i in range(30)]
            await asyncio.sleep(0.2)

            # Mid-traffic growth past the cap: the next dispatch
            # overflows and the server must fall back, not fail.
            extra = 300
            rids = np.full(extra, res.store._rid, np.int32)
            cids = np.array(
                [engine.client_handle(f"ovf{i}") for i in range(extra)],
                np.int64,
            )
            engine.bulk_assign(
                rids, cids, np.full(extra, time.time() + 60.0),
                np.full(extra, 1.0), np.zeros(extra),
                np.full(extra, 2.0), np.ones(extra, np.int32),
            )
            assert engine.max_leases > DENSE_MAX_K

            for _ in range(400):
                if (
                    server._resident_wide is not None
                    and server._resident_wide.ticks >= 3
                ):
                    break
                await asyncio.sleep(0.05)
            stop[0] = True
            await asyncio.gather(*loops)

            # The switch happened: the wide chunked solver took the
            # resource over, the resident path stayed on, traffic
            # unharmed.
            assert server._resident_wide is not None
            assert server._resident_wide.ticks >= 3
            assert server._resident_ok
            assert "big" in server._wide_ids
            assert not errors, errors[:5]

            # No grant lost or doubled across the switch: the store's
            # running aggregate equals the per-lease sum exactly, every
            # client holds exactly one lease, and the resource is not
            # oversubscribed.
            leases = dict(res.store.items())
            assert len(leases) == len(res.store)
            lease_sum = sum(l.has for l in leases.values())
            assert abs(lease_sum - res.store.sum_has) < 1e-6
            cap = res.template.capacity
            assert res.store.sum_has <= cap + 1e-6
            # Demand fits capacity here, so post-switch solves must
            # still hand every live client its wants (nothing lost).
            out = await stub.GetCapacity(request(0))
            assert out.response[0].gets.capacity > 0.0

        await server.stop()

    asyncio.run(body())


def test_mastership_flip_drops_stale_resident_handle():
    """A mastership flip swaps the store engine mid-flight; a tick
    handle produced by the PRE-flip resident solver must be dropped by
    the next tick, never collected — its row ids belong to the orphaned
    engine, and applying it would write pre-failover grants into the
    fresh master's store (which must start empty, in learning). Pins
    the solver-identity guard in CapacityServer._resident_step."""

    async def body():
        server = CapacityServer(
            "flip", TrivialElection(), mode="batch", tick_interval=10.0,
            minimum_refresh_interval=0.0, native_store=True,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        addr = f"127.0.0.1:{port}"

        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)
            for i in range(8):
                req = pb.GetCapacityRequest(client_id=f"c{i}")
                rr = req.resource.add()
                rr.resource_id = "shared0"
                rr.wants = 10.0
                await stub.GetCapacity(req)
        await server.tick_once()
        await server.tick_once()
        old_solver = server._resident
        assert old_solver is not None

        # The race under test: the executor thread finishes a dispatch
        # with the OLD solver and attaches its handle AFTER the flip
        # cleared the slot.
        lane_res = list(server.resources.values())
        stale = old_solver.dispatch(lane_res, server._config_epoch)
        await server._on_is_master(False)
        await server._on_is_master(True)
        server._resident_handle = (old_solver, stale)

        # A fresh client population on the fresh engine, then a tick:
        # the stale handle must be dropped uncollected, and the new
        # solver must be a new instance on the new engine.
        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)
            for i in range(8):
                req = pb.GetCapacityRequest(client_id=f"n{i}")
                rr = req.resource.add()
                rr.resource_id = "shared0"
                rr.wants = 5.0
                await stub.GetCapacity(req)
        await server.tick_once()
        assert stale.collected is False, (
            "stale pre-flip handle was collected into the new engine"
        )
        assert server._resident is not None
        assert server._resident is not old_solver
        # And the pipeline keeps working on the new engine.
        await server.tick_once()
        assert server._resident.ticks >= 1
        await server.stop()

    asyncio.run(body())


def test_concurrent_tick_once_calls_serialize():
    """tick_once driven directly (tests, tooling) can race the server's
    own tick loop; overlapping ticks would consume the resident
    solver's donated device buffers twice (XLA InvalidArgument) and
    interleave snapshot/apply. They must queue instead: N concurrent
    calls all complete and each runs a full tick."""

    async def body():
        server = CapacityServer(
            "serial", TrivialElection(), mode="batch", tick_interval=60.0,
            minimum_refresh_interval=0.0, native_store=True,
        )
        port = await server.start(0, host="127.0.0.1")
        await server.load_config(parse_yaml_config(CONFIG))
        await asyncio.sleep(0)
        addr = f"127.0.0.1:{port}"
        async with grpc.aio.insecure_channel(addr) as ch:
            stub = CapacityStub(ch)
            for i in range(8):
                req = pb.GetCapacityRequest(client_id=f"c{i}")
                rr = req.resource.add()
                rr.resource_id = "shared0"
                rr.wants = 10.0
                await stub.GetCapacity(req)
        before = server._ticks_done
        await asyncio.gather(*(server.tick_once() for _ in range(5)))
        # Every call ran one full (serialized) tick; the pipelined
        # resident path counts a tick at each collect, so at least the
        # calls minus the pipeline's one in-flight handle must land.
        assert server._ticks_done >= before + 4
        await server.stop()

    asyncio.run(body())
